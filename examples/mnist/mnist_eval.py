"""MNIST with a sidecar evaluator node (``eval_node=True``).

Parity: reference examples/mnist/estimator/mnist_tf.py:107 — the
estimator example runs `train_and_evaluate` with a dedicated evaluator
task (`TFCluster.run(..., eval_node=True)`, reference
examples/mnist/estimator/mnist_tf.py:116).  The TPU-first re-design
keeps the role but drops the Estimator machinery: the chief writes
step-stamped checkpoints (utils.checkpoint.save_checkpoint) and the
evaluator is a sidecar loop that polls the checkpoint dir, evaluates
each new step on a held-out set, and appends one JSON line per
evaluation — the TF2 `SidecarEvaluator` pattern, no train-loop
coupling.

    python examples/mnist/mnist_eval.py --cluster_size 3 --steps 40

cluster_size counts ALL nodes: 1 evaluator + 1 chief + workers.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _eval_loop(args, ctx):
    """Evaluator role: not part of the SPMD job (owns no chips); polls
    checkpoints until the chief publishes the DONE marker, then drains
    whatever checkpoint is newest and exits."""
    import numpy as np
    import jax

    from mnist_data_setup import synthetic_mnist
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    images, labels = synthetic_mnist(args["num_examples"], seed=1)  # held-out
    apply_fn = jax.jit(mnist.apply)
    log_path = os.path.join(args["model_dir"], "eval_results.jsonl")
    done_path = os.path.join(args["model_dir"], "DONE")
    ckpt_dir = os.path.join(args["model_dir"], "ckpt")

    seen = -1
    deadline = time.monotonic() + args["eval_timeout"]
    while True:
        latest = ckpt.latest_checkpoint(ckpt_dir)
        step = ckpt.step_of(latest) if latest else -1
        if latest and step > seen:
            params = ckpt.load_checkpoint(latest)
            logits = np.asarray(apply_fn(params, images))
            acc = float((logits.argmax(-1) == labels).mean())
            rec = {"step": step, "accuracy": acc, "examples": len(labels)}
            with open(log_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(f"evaluator: step {step} accuracy={acc:.3f}", flush=True)
            seen = step
            # the timeout bounds IDLE time, not total run time: a long
            # training run with steady checkpoints is healthy progress
            deadline = time.monotonic() + args["eval_timeout"]
            continue  # immediately re-check: never sleep behind a backlog
        if os.path.exists(done_path):
            # ack AFTER draining the newest checkpoint: the chief blocks
            # on this marker so shutdown can never kill a mid-flight
            # final evaluation (the evaluator child is a daemon process)
            tmp = os.path.join(args["model_dir"], f".eval_done.{os.getpid()}")
            with open(tmp, "w") as f:
                f.write(str(seen))
            os.replace(tmp, os.path.join(args["model_dir"], "EVAL_DONE"))
            print(f"evaluator: DONE after step {seen}", flush=True)
            return seen
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"evaluator: no DONE marker within {args['eval_timeout']}s")
        time.sleep(0.2)


def main_fun(args, ctx):
    if ctx.job_name == "evaluator":
        return _eval_loop(args, ctx)

    import numpy as np
    import jax
    import optax

    from mnist_data_setup import synthetic_mnist
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel import make_mesh, local_to_global
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})

    # shard by the contiguous SPMD process id, NOT ctx.task_index:
    # task_index is per-job, so with a chief role chief:0 and worker:0
    # would both select shard 0 and one shard would never be trained
    images, labels = synthetic_mnist(args["num_examples"], seed=0)
    shard = (np.arange(len(images)) % env["num_processes"]
             == env["process_id"])
    images, labels = images[shard], labels[shard]

    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(args["lr"], momentum=0.9)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))

    ckpt_dir = os.path.join(args["model_dir"], "ckpt")
    per_proc = args["batch_size"] // max(env["num_processes"], 1)
    rng = np.random.default_rng(ctx.task_index)
    loss = acc = 0.0
    for step in range(1, args["steps"] + 1):
        idx = rng.integers(0, len(images), per_proc)
        gi, gl = local_to_global(
            mesh, (images[idx], labels[idx].astype(np.int32)))
        params, opt_state, loss, acc = step_fn(params, opt_state, gi, gl)
        if step % args["ckpt_steps"] == 0 and ckpt.is_chief(ctx):
            ckpt.save_checkpoint(ckpt_dir, params, step)

    if ckpt.is_chief(ctx):
        if args["steps"] % args["ckpt_steps"] != 0:
            ckpt.save_checkpoint(ckpt_dir, params, args["steps"])
        # atomic DONE publish AFTER the final checkpoint: the evaluator
        # drains the newest step before honoring the marker
        tmp = os.path.join(args["model_dir"], f".done.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write("done")
        os.replace(tmp, os.path.join(args["model_dir"], "DONE"))
        # hold the worker slot open until the evaluator acks: shutdown
        # fires once workers return, and must not reap a final eval
        ack = os.path.join(args["model_dir"], "EVAL_DONE")
        deadline = time.monotonic() + args["eval_timeout"]
        while not os.path.exists(ack):
            if time.monotonic() > deadline:
                raise TimeoutError("evaluator never acked DONE")
            time.sleep(0.2)
    return float(acc)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=3,
                   help="total nodes: 1 evaluator + 1 chief + workers")
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--ckpt_steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--num_examples", type=int, default=2048)
    p.add_argument("--eval_timeout", type=float, default=300.0)
    p.add_argument("--model_dir", default="/tmp/mnist_model_eval")
    args = p.parse_args()

    from tensorflowonspark_tpu import cluster as TFCluster, configure_logging
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine

    configure_logging()
    os.makedirs(args.model_dir, exist_ok=True)
    # a reused model_dir must start clean: a stale DONE/EVAL_DONE pair
    # makes the evaluator exit immediately and the chief's ack-wait pass
    # on the previous run's marker, and old checkpoints (step >= this
    # run's) would shadow every new one under the `step > seen` rule
    import contextlib
    import shutil

    for marker in ("DONE", "EVAL_DONE", "eval_results.jsonl"):
        with contextlib.suppress(FileNotFoundError):
            os.remove(os.path.join(args.model_dir, marker))
    shutil.rmtree(os.path.join(args.model_dir, "ckpt"), ignore_errors=True)
    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    cluster = TFCluster.run(
        engine, main_fun, vars(args), num_executors=args.cluster_size,
        input_mode=InputMode.TENSORFLOW, master_node="chief",
        eval_node=True,
    )
    cluster.shutdown(grace_secs=2)
    engine.stop()
    log = os.path.join(args.model_dir, "eval_results.jsonl")
    with open(log) as f:
        evals = [json.loads(ln) for ln in f]
    print(f"evaluations: {[(e['step'], round(e['accuracy'], 3)) for e in evals]}")


if __name__ == "__main__":
    main()
