"""MNIST parallel inference: N independent single-node workers, no cluster
(parity: reference examples/mnist/keras/mnist_inference.py:79, which uses
TFParallel.run under Spark barrier scheduling).

Each worker loads the exported model, scores its shard of the TFRecords,
and writes a predictions file.

    python examples/mnist/mnist_data_setup.py --output /tmp/mnist
    python examples/mnist/mnist_tf.py            # produces the export
    python examples/mnist/mnist_inference.py \\
        --data_dir /tmp/mnist/tfr --export_dir /tmp/mnist_model_tf/export
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def infer_fun(args, ctx):
    import numpy as np

    from tensorflowonspark_tpu import recordio
    from tensorflowonspark_tpu.utils.checkpoint import load_exported

    params, meta = load_exported(args["export_dir"])
    import importlib

    mod, _, fn = meta["predict"].partition(":")
    predict = getattr(importlib.import_module(mod), fn)

    files = sorted(
        os.path.join(args["data_dir"], f)
        for f in os.listdir(args["data_dir"]) if f.startswith("part-")
    )[ctx.task_index::ctx.num_workers]

    os.makedirs(args["output"], exist_ok=True)
    out_path = os.path.join(args["output"], f"part-{ctx.task_index:05d}")
    n = 0
    with open(out_path, "w") as out:
        for path in files:
            images, labels = [], []
            for rec in recordio.TFRecordReader(path):
                feats = recordio.decode_example(rec)
                images.append(np.asarray(feats["image"][1], np.float32))
                labels.append(int(feats["label"][1][0]))
            if not images:
                continue
            res = predict(params, {"x": np.stack(images)})
            for lbl, pred in zip(labels, res["prediction"]):
                out.write(f"{lbl} {int(pred)}\n")
                n += 1
    return n


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--data_dir", default="/tmp/mnist/tfr")
    p.add_argument("--export_dir", default="/tmp/mnist_model_tf/export")
    p.add_argument("--output", default="/tmp/mnist_predictions")
    args = p.parse_args()

    from tensorflowonspark_tpu import configure_logging, parallel_run
    from tensorflowonspark_tpu.engine import LocalEngine

    configure_logging()
    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": "",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    counts = parallel_run.run(
        engine, infer_fun, vars(args), num_executors=args.cluster_size
    )
    engine.stop()
    print(f"wrote {sum(counts)} predictions to {args.output}")


if __name__ == "__main__":
    main()
