"""MNIST, InputMode.TENSORFLOW over TFRecords: each worker reads a disjoint
subset of the TFRecord shards through the native record reader
(parity: reference examples/mnist/keras/mnist_tf_ds.py, which builds a
sharded tf.data pipeline from HDFS TFRecords and resolves paths with
``ctx.absolute_path`` :41).

    python examples/mnist/mnist_data_setup.py --output /tmp/mnist
    python examples/mnist/mnist_tf_ds.py --data_dir /tmp/mnist/tfr
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    import numpy as np
    import jax
    import optax

    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel import make_mesh, local_to_global
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})

    # shard the part files over workers (hosts own disjoint file sets)
    data_dir = ctx.absolute_path(args["data_dir"])
    if data_dir.startswith("file://"):  # local FS: strip scheme for os IO
        data_dir = data_dir[len("file://"):]
    files = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir)
        if f.startswith("part-")
    )[ctx.task_index::ctx.num_workers]
    # bulk columnar load over this worker's shard subset: one C pass per
    # shard straight into dense arrays (~5x the per-row decode loop);
    # empty parts are skipped and cross-shard schema drift errors clearly
    cols = dfutil.load_tfrecords_columnar(files)
    if not cols:
        raise RuntimeError(
            f"worker {ctx.task_index}/{ctx.num_workers} got no data: "
            f"shard subset {files or '(empty)'} — fewer non-empty part "
            "files than workers?")
    images = np.asarray(cols["image"], np.float32).reshape(-1, 28, 28, 1)
    labels = np.asarray(cols["label"], np.int32)
    assert labels.ndim == 1, f"expected scalar labels, got {labels.shape}"
    print(f"worker {ctx.task_index}: {len(images)} examples from "
          f"{len(files)} shards")

    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(args["lr"], momentum=0.9)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))
    per_proc = args["batch_size"] // max(env["num_processes"], 1)
    rng = np.random.default_rng(ctx.task_index)
    loss = acc = 0.0
    for step in range(1, args["steps"] + 1):
        idx = rng.integers(0, len(images), per_proc)
        gi, gl = local_to_global(mesh, (images[idx], labels[idx]))
        params, opt_state, loss, acc = step_fn(params, opt_state, gi, gl)
        if step % 10 == 0 and ctx.task_index == 0:
            print(f"step {step}: loss={float(loss):.4f} acc={float(acc):.3f}")

    if ckpt.is_chief(ctx):
        ckpt.export_model(
            os.path.join(args["model_dir"], "export"), params, ctx,
            metadata={"predict": "tensorflowonspark_tpu.models.mnist:predict"},
        )
    return float(acc)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data_dir", default="/tmp/mnist/tfr")
    p.add_argument("--model_dir", default="/tmp/mnist_model_tf_ds")
    args = p.parse_args()

    from tensorflowonspark_tpu import cluster as TFCluster, configure_logging
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine

    configure_logging()
    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": "",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    cluster = TFCluster.run(
        engine, main_fun, vars(args), num_executors=args.cluster_size,
        input_mode=InputMode.TENSORFLOW, master_node="chief",
    )
    cluster.shutdown(grace_secs=2)
    engine.stop()
    print("export:", os.path.join(args.model_dir, "export"))


if __name__ == "__main__":
    main()
