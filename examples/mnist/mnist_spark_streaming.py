"""MNIST streaming: online training over a stream of micro-batches
(parity: reference examples/mnist/estimator/mnist_spark_streaming.py —
DStream feeding with graceful STOP via the rendezvous server; stop it
from another shell with examples/utils/stop_streaming.py).

    python examples/mnist/mnist_spark_streaming.py --cluster_size 2 \\
        --micro_batches 20
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    import numpy as np
    import jax
    import optax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel import make_mesh, local_to_global
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(args["lr"], momentum=0.9)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))

    feed = ctx.get_data_feed(train_mode=True)
    per_proc = max(args["batch_size"] // max(env["num_processes"], 1), 1)
    step = 0
    while not feed.should_stop():
        batch = feed.next_batch(per_proc)
        if not batch:
            continue
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        labels = np.asarray([b[1] for b in batch], dtype=np.int32)
        if len(batch) < per_proc:  # pad the short tail of a micro-batch
            reps = -(-per_proc // len(batch))
            images = np.tile(images, (reps, 1, 1, 1))[:per_proc]
            labels = np.tile(labels, reps)[:per_proc]
        gi, gl = local_to_global(mesh, (images, labels))
        params, opt_state, loss, acc = step_fn(params, opt_state, gi, gl)
        step += 1
        if step % 10 == 0 and ctx.task_index == 0:
            print(f"stream step {step}: loss={float(loss):.4f}")

    if ckpt.is_chief(ctx):
        ckpt.export_model(
            os.path.join(args["model_dir"], "export"), params, ctx,
            metadata={"predict": "tensorflowonspark_tpu.models.mnist:predict"},
        )


def micro_batch_stream(engine, args):
    """A generator of datasets — the DStream analogue.  A real Spark
    deployment passes the DStream's RDDs; here micro-batches arrive on a
    timer."""
    from mnist_data_setup import synthetic_mnist

    for i in range(args.micro_batches):
        images, labels = synthetic_mnist(args.batch_size * 2, seed=i)
        records = list(zip(list(images), list(labels)))
        yield engine.parallelize(records, args.cluster_size)
        time.sleep(args.interval)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--micro_batches", type=int, default=20)
    p.add_argument("--interval", type=float, default=0.0,
                   help="seconds between micro-batches")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--model_dir", default="/tmp/mnist_model_streaming")
    args = p.parse_args()

    from tensorflowonspark_tpu import cluster as TFCluster, configure_logging
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine

    configure_logging()
    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    cluster = TFCluster.run(
        engine, main_fun,
        {"batch_size": args.batch_size, "lr": args.lr,
         "model_dir": args.model_dir},
        num_executors=args.cluster_size, input_mode=InputMode.SPARK,
        master_node="chief",
    )
    host, port = cluster.cluster_meta["server_addr"]
    print(f"rendezvous server at {host}:{port} — stop early with:\n"
          f"  python examples/utils/stop_streaming.py {host} {port}")
    cluster.train_stream(micro_batch_stream(engine, args))
    cluster.shutdown(grace_secs=5)
    engine.stop()
    print("export:", os.path.join(args.model_dir, "export"))


if __name__ == "__main__":
    main()
