"""MNIST, InputMode.TENSORFLOW: every node reads its own data shard directly
(parity: reference examples/mnist/keras/mnist_tf.py — no feeders; the
cluster only provides rendezvous + roles and each worker builds its own
input pipeline).

    python examples/mnist/mnist_tf.py --cluster_size 2 --steps 40
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    import numpy as np
    import jax
    import optax

    from mnist_data_setup import synthetic_mnist
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel import make_mesh, local_to_global

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})

    # host-sharded input pipeline: each process owns a disjoint slice.
    # Shard by the contiguous SPMD process id, NOT ctx.task_index —
    # task_index is per-job, so with master_node="chief" chief:0 and
    # worker:0 would both read shard 0 and one shard would go unread.
    images, labels = synthetic_mnist(args["num_examples"], seed=0)
    shard = (np.arange(len(images)) % env["num_processes"]
             == env["process_id"])
    images, labels = images[shard], labels[shard]

    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(args["lr"], momentum=0.9)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))

    per_proc = args["batch_size"] // max(env["num_processes"], 1)
    rng = np.random.default_rng(ctx.task_index)
    loss = acc = 0.0
    for step in range(1, args["steps"] + 1):
        idx = rng.integers(0, len(images), per_proc)
        gi, gl = local_to_global(
            mesh, (images[idx], labels[idx].astype(np.int32))
        )
        params, opt_state, loss, acc = step_fn(params, opt_state, gi, gl)
        if step % 10 == 0 and ctx.task_index == 0:
            print(f"step {step}: loss={float(loss):.4f} acc={float(acc):.3f}")

    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    if ckpt.is_chief(ctx):
        ckpt.export_model(
            os.path.join(args["model_dir"], "export"), params, ctx,
            metadata={"predict": "tensorflowonspark_tpu.models.mnist:predict"},
        )
    return float(acc)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--num_examples", type=int, default=2048)
    p.add_argument("--model_dir", default="/tmp/mnist_model_tf")
    args = p.parse_args()

    from tensorflowonspark_tpu import cluster as TFCluster, configure_logging
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine

    configure_logging()
    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    cluster = TFCluster.run(
        engine, main_fun, vars(args), num_executors=args.cluster_size,
        input_mode=InputMode.TENSORFLOW, master_node="chief",
    )
    cluster.shutdown(grace_secs=2)
    engine.stop()
    print("export:", os.path.join(args.model_dir, "export"))


if __name__ == "__main__":
    main()
