"""Prepare MNIST-shaped data as CSV and TFRecords
(parity: reference examples/mnist/mnist_data_setup.py:41-65, which writes
RDD CSV + TFRecords via the Hadoop OutputFormat).

This environment has no egress, so by default a deterministic synthetic
set with learnable structure is generated (same generator as
mnist_spark.py); pass --from_csv to convert a real MNIST CSV dump
(label,pix0,...,pix783 per line) instead.

    python examples/mnist/mnist_data_setup.py --output /tmp/mnist
    # -> /tmp/mnist/csv/part-00000...  /tmp/mnist/tfr/part-r-...
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_mnist(n, seed=0):
    """(images [n,28,28,1] float32 in [0,1], labels [n] int32 0..7):
    label = argmax quadrant brightness x overall-brightness bit."""
    rng = np.random.default_rng(seed)
    images = rng.random((n, 28, 28, 1), dtype=np.float32)
    q = np.stack(
        [images[:, :14, :14, 0].mean((1, 2)), images[:, :14, 14:, 0].mean((1, 2)),
         images[:, 14:, :14, 0].mean((1, 2)), images[:, 14:, 14:, 0].mean((1, 2))],
        axis=-1)
    labels = (np.argmax(q, axis=-1) * 2 + (q.sum(-1) > 2.0)).astype(np.int32)
    return images, labels


def load_csv_dir(csv_dir):
    rows = []
    for fname in sorted(os.listdir(csv_dir)):
        with open(os.path.join(csv_dir, fname)) as f:
            for line in f:
                vals = np.fromstring(line, dtype=np.float32, sep=",")
                rows.append((vals[1:].reshape(28, 28, 1) / 255.0, int(vals[0])))
    images = np.stack([r[0] for r in rows]).astype(np.float32)
    labels = np.asarray([r[1] for r in rows], dtype=np.int32)
    return images, labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--output", default="/tmp/mnist")
    p.add_argument("--num_examples", type=int, default=2048)
    p.add_argument("--num_partitions", type=int, default=4)
    p.add_argument("--from_csv", default=None,
                   help="existing MNIST CSV dir to convert instead of synthetic")
    args = p.parse_args()

    from tensorflowonspark_tpu import dfutil

    if args.from_csv:
        images, labels = load_csv_dir(args.from_csv)
    else:
        images, labels = synthetic_mnist(args.num_examples)

    # CSV shards (label,pix...) — the reference's RDD-of-CSV format
    csv_dir = os.path.join(args.output, "csv")
    os.makedirs(csv_dir, exist_ok=True)
    per = (len(images) + args.num_partitions - 1) // args.num_partitions
    for shard in range(args.num_partitions):
        lo, hi = shard * per, min((shard + 1) * per, len(images))
        with open(os.path.join(csv_dir, f"part-{shard:05d}"), "w") as f:
            for i in range(lo, hi):
                pix = ",".join(
                    str(int(v)) for v in (images[i].ravel() * 255).astype(np.int64)
                )
                f.write(f"{labels[i]},{pix}\n")

    # TFRecords via the native writer (tensorflow-hadoop jar equivalent)
    tfr_dir = os.path.join(args.output, "tfr")
    rows = [
        {"image": [float(v) for v in images[i].ravel()], "label": int(labels[i])}
        for i in range(len(images))
    ]
    dfutil.save_as_tfrecords(rows, tfr_dir)
    print(f"wrote {len(images)} examples: {csv_dir} and {tfr_dir}")


if __name__ == "__main__":
    main()
