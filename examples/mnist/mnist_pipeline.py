"""MNIST via the ML Pipeline API: TFEstimator.fit -> TFModel.transform
(parity: reference examples/mnist/keras/mnist_pipeline.py — Estimator
trains over InputMode.SPARK feeding, chief exports, Model runs
cached-model batch inference per worker).

    python examples/mnist/mnist_pipeline.py --cluster_size 2 --steps 30
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def train_fun(args, ctx):
    import numpy as np
    import jax
    import optax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel import make_mesh, local_to_global
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(args.lr, momentum=0.9)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))

    feed = ctx.get_data_feed(train_mode=True)
    per_proc = args.batch_size // max(env["num_processes"], 1)
    while not feed.should_stop():
        batch = feed.next_batch(per_proc)
        if len(batch) < per_proc:
            continue
        # rows arrive as (image-flat-784, label) tuples from the dataset
        images = np.asarray([b[0] for b in batch], np.float32).reshape(
            -1, 28, 28, 1
        )
        labels = np.asarray([b[1] for b in batch], np.int32)
        gi, gl = local_to_global(mesh, (images, labels))
        params, opt_state, loss, acc = step_fn(params, opt_state, gi, gl)

    if ckpt.is_chief(ctx):
        ckpt.export_model(
            args.export_dir, params, ctx,
            metadata={"predict": "tensorflowonspark_tpu.models.mnist:predict"},
        )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--export_dir", default="/tmp/mnist_pipeline/export")
    args = p.parse_args()

    import numpy as np

    from tensorflowonspark_tpu import configure_logging, pipeline
    from tensorflowonspark_tpu.engine import LocalEngine
    from mnist_data_setup import synthetic_mnist

    configure_logging()
    images, labels = synthetic_mnist(args.batch_size * args.steps)
    rows = [
        (img.ravel().tolist(), int(lbl)) for img, lbl in zip(images, labels)
    ]

    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": "",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    ds = engine.parallelize(rows, args.cluster_size * 2)

    estimator = (
        pipeline.TFEstimator(train_fun, vars(args))
        .setClusterSize(args.cluster_size)
        .setEpochs(args.epochs)
        .setBatchSize(args.batch_size)
        .setExportDir(args.export_dir)
    )
    model = estimator.fit(ds)

    model = (
        model.setBatchSize(args.batch_size)
        .setInputMapping({"image": "x"})
        .setOutputMapping({"prediction": "pred"})
    )
    test_rows = [{"image": r[0], "label": r[1]} for r in rows[:256]]
    preds = model.transform(engine.parallelize(test_rows, 2)).collect()
    correct = sum(
        int(p["pred"]) == r["label"] for p, r in zip(preds, test_rows)
    )
    engine.stop()
    print(f"accuracy on {len(preds)} training rows: {correct / len(preds):.3f}")


if __name__ == "__main__":
    main()
