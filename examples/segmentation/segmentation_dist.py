"""U-Net segmentation on a device mesh — step 2 of the conversion ladder
(parity: reference examples/segmentation/segmentation_dist.py, which adds
TF_CONFIG + MultiWorkerMirroredStrategy; here the same delta is a mesh +
sharded batch: ~6 changed lines from segmentation.py).

    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \\
        python examples/segmentation/segmentation_dist.py --steps 10
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from segmentation import synthetic_pets


def train(args):
    import jax

    if os.environ.get("JAX_PLATFORMS"):  # site hook may force TPU platform
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.models import segmentation
    from tensorflowonspark_tpu.parallel import make_mesh

    mesh = make_mesh({"data": -1})                                   # (+1)
    bsh = NamedSharding(mesh, P("data"))                             # (+2)

    images, masks = synthetic_pets(args.batch_size * 4, hw=args.image_size)
    params, state = segmentation.init(
        jax.random.PRNGKey(0), num_classes=3, width=args.width
    )
    opt = optax.adam(args.lr)
    opt_state = opt.init(params)
    step_fn = jax.jit(segmentation.make_train_step(opt))

    rng = np.random.default_rng(0)
    for step in range(1, args.steps + 1):
        idx = rng.integers(0, len(images), args.batch_size)
        gi = jax.device_put(images[idx], bsh)                        # (+3)
        gm = jax.device_put(masks[idx], bsh)                         # (+4)
        params, state, opt_state, loss = step_fn(
            params, state, opt_state, gi, gm
        )
        if step % 5 == 0:
            print(f"step {step}: loss={float(loss):.4f} "
                  f"(mesh={dict(mesh.shape)})")
    return params, state


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--image_size", type=int, default=64)
    p.add_argument("--width", type=float, default=0.5)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()
    train(args)


if __name__ == "__main__":
    main()
