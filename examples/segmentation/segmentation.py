"""U-Net segmentation, single process — step 1 of the conversion ladder
(parity: reference examples/segmentation/segmentation.py; the reference's
3-step story is single-process → TF_CONFIG distributed → TFoS; here:
single-process → multi-chip mesh (segmentation_dist.py) → cluster-fed
(segmentation_spark.py)).

    python examples/segmentation/segmentation.py --steps 10
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_pets(n, hw=64, seed=0):
    """Images with a bright disc; mask = {0: background, 1: disc, 2: rim}."""
    rng = np.random.default_rng(seed)
    images = rng.random((n, hw, hw, 3), dtype=np.float32) * 0.3
    masks = np.zeros((n, hw, hw), dtype=np.int32)
    yy, xx = np.mgrid[:hw, :hw]
    for i in range(n):
        cy, cx = rng.integers(hw // 4, 3 * hw // 4, 2)
        r = int(rng.integers(hw // 8, hw // 4))
        d2 = (yy - cy) ** 2 + (xx - cx) ** 2
        disc, rim = d2 <= (r - 2) ** 2, (d2 > (r - 2) ** 2) & (d2 <= r**2)
        images[i][disc] += 0.6
        images[i][rim] += 0.3
        masks[i][disc], masks[i][rim] = 1, 2
    return np.clip(images, 0, 1), masks


def train(args):
    import jax
    import optax

    from tensorflowonspark_tpu.models import segmentation

    images, masks = synthetic_pets(args.batch_size * 4, hw=args.image_size)
    params, state = segmentation.init(
        jax.random.PRNGKey(0), num_classes=3, width=args.width
    )
    opt = optax.adam(args.lr)
    opt_state = opt.init(params)
    step_fn = jax.jit(segmentation.make_train_step(opt))

    rng = np.random.default_rng(0)
    for step in range(1, args.steps + 1):
        idx = rng.integers(0, len(images), args.batch_size)
        params, state, opt_state, loss = step_fn(
            params, state, opt_state, images[idx], masks[idx]
        )
        if step % 5 == 0:
            print(f"step {step}: loss={float(loss):.4f}")
    return params, state


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--image_size", type=int, default=64)
    p.add_argument("--width", type=float, default=0.5)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()
    train(args)


if __name__ == "__main__":
    main()
