"""U-Net segmentation, cluster-fed — step 3 of the conversion ladder
(parity: reference examples/segmentation/segmentation_spark.py: the
dist version's training loop, with the input pipeline swapped for the
cluster DataFeed and an extra ~10 lines of launch plumbing).

    python examples/segmentation/segmentation_spark.py --cluster_size 2 \\
        --steps 6
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    import numpy as np
    import jax
    import optax

    from tensorflowonspark_tpu.models import segmentation
    from tensorflowonspark_tpu.parallel import local_to_global, make_mesh
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})
    params, state = segmentation.init(
        jax.random.PRNGKey(0), num_classes=3, width=args["width"]
    )
    opt = optax.adam(args["lr"])
    opt_state = opt.init(params)
    step_fn = jax.jit(segmentation.make_train_step(opt))

    feed = ctx.get_data_feed(train_mode=True)
    per_proc = args["batch_size"] // max(env["num_processes"], 1)
    step = 0
    while not feed.should_stop():
        batch = feed.next_batch(per_proc)
        if len(batch) < per_proc:
            continue
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        masks = np.stack([b[1] for b in batch]).astype(np.int32)
        gi, gm = local_to_global(mesh, (images, masks))
        params, state, opt_state, loss = step_fn(
            params, state, opt_state, gi, gm
        )
        step += 1
        if step % 5 == 0 and ctx.task_index == 0:
            print(f"step {step}: loss={float(loss):.4f}")

    if ckpt.is_chief(ctx):
        ckpt.save_checkpoint(
            os.path.join(args["model_dir"], "ckpt"), params, step
        )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--image_size", type=int, default=64)
    p.add_argument("--width", type=float, default=0.5)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--model_dir", default="/tmp/segmentation_model")
    args = p.parse_args()

    from tensorflowonspark_tpu import cluster as TFCluster, configure_logging
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine
    from segmentation import synthetic_pets

    configure_logging()
    images, masks = synthetic_pets(
        args.batch_size * args.steps, hw=args.image_size
    )
    records = list(zip(list(images), list(masks)))

    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": "",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    cluster = TFCluster.run(
        engine, main_fun,
        {"batch_size": args.batch_size, "lr": args.lr,
         "width": args.width, "model_dir": args.model_dir},
        num_executors=args.cluster_size, input_mode=InputMode.SPARK,
        master_node="chief",
    )
    cluster.train(engine.parallelize(records, args.cluster_size * 2))
    cluster.shutdown(grace_secs=5)
    engine.stop()


if __name__ == "__main__":
    main()
