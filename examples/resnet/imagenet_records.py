"""Shared ImageNet record decode for the prep tool and the training
example — ONE definition of what a valid record is, so the dataset
written by ``imagenet_data_setup.py`` and the records accepted by
``resnet_imagenet_spark.py`` can never drift apart.

Two layouts are understood: this repo's writers ("image" bytes +
"label", 0-based) and the TF-official ImageNet keys ("image/encoded"
JPEG/PNG bytes + "image/class/label", 1-based).
"""

import io

import numpy as np

_JPEG_MAGIC = b"\xff\xd8"
_PNG_MAGIC = b"\x89PNG"


def decode_record(feats, image_size):
    """Normalize one record to ``(uint8 [H, W, 3] array, 0-based int)``.

    ``feats``: {name: value} or {name: [value]} (both the dfutil-loaded
    and raw decode_example shapes).  Raises KeyError when image/label
    fields are missing and ValueError when the payload is neither an
    exact-size raw buffer nor JPEG/PNG — callers choose skip vs fail.

    Payload rule (order matters): JPEG/PNG magic wins over the size
    heuristic — a compressed image whose byte length happens to equal
    H*W*3 must be decoded, not baked into the dataset as garbage
    "raw" pixels.
    """
    data = feats.get("image", feats.get("image/encoded"))
    if data is None:
        raise KeyError(
            f"record has neither 'image' nor 'image/encoded' features "
            f"(got {sorted(feats)})")
    if isinstance(data, list):
        data = data[0]
    if "label" in feats:
        label = feats["label"]
    elif "image/class/label" in feats:
        label = feats["image/class/label"]
        label = (label[0] if isinstance(label, list) else label) - 1
    else:
        raise KeyError(
            f"record has neither 'label' nor 'image/class/label' "
            f"(got {sorted(feats)})")
    if isinstance(label, list):
        label = label[0]

    if data[:2] == _JPEG_MAGIC:
        # native libjpeg path when built (DCT-scaled decode + C resize,
        # GIL-free — recordio/jpeg.py).  The native decoder is strict;
        # anything it refuses (CMYK, warning-emitting streams) retries
        # through PIL inside decode_resized, so valid-but-odd images
        # still decode and corrupt ones still raise ValueError.
        from tensorflowonspark_tpu.recordio import jpeg as _jpeg

        return _jpeg.decode_resized(data, image_size), int(label)
    if data[:4] == _PNG_MAGIC:
        from PIL import Image  # host-side decode, one per record

        img = Image.open(io.BytesIO(data)).convert("RGB")
        if img.size != (image_size, image_size):
            img = img.resize((image_size, image_size), Image.BILINEAR)
        return np.asarray(img, np.uint8), int(label)
    raw = np.frombuffer(data, dtype=np.uint8)
    if raw.size == image_size * image_size * 3:
        return raw.reshape(image_size, image_size, 3), int(label)
    raise ValueError(
        f"image payload is {raw.size} bytes: neither "
        f"{image_size}x{image_size}x3 raw uint8 nor JPEG/PNG — check "
        f"--image_size against the dataset")
