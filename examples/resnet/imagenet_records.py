"""Shared ImageNet record decode for the prep tool and the training
example — ONE definition of what a valid record is, so the dataset
written by ``imagenet_data_setup.py`` and the records accepted by
``resnet_imagenet_spark.py`` can never drift apart.

Two layouts are understood: this repo's writers ("image" bytes +
"label", 0-based) and the TF-official ImageNet keys ("image/encoded"
JPEG/PNG bytes + "image/class/label", 1-based).
"""

import io

import numpy as np

_JPEG_MAGIC = b"\xff\xd8"
_PNG_MAGIC = b"\x89PNG"


def _payload_label(feats):
    """Field/label normalization shared by the single and batch decoders:
    {name: value} or {name: [value]} → (image payload, 0-based label)."""
    data = feats.get("image", feats.get("image/encoded"))
    if data is None:
        raise KeyError(
            f"record has neither 'image' nor 'image/encoded' features "
            f"(got {sorted(feats)})")
    if isinstance(data, list):
        data = data[0]
    if "label" in feats:
        label = feats["label"]
    elif "image/class/label" in feats:
        label = feats["image/class/label"]
        label = (label[0] if isinstance(label, list) else label) - 1
    else:
        raise KeyError(
            f"record has neither 'label' nor 'image/class/label' "
            f"(got {sorted(feats)})")
    if isinstance(label, list):
        label = label[0]
    return data, label


def decode_record(feats, image_size):
    """Normalize one record to ``(uint8 [H, W, 3] array, 0-based int)``.

    ``feats``: {name: value} or {name: [value]} (both the dfutil-loaded
    and raw decode_example shapes).  Raises KeyError when image/label
    fields are missing and ValueError when the payload is neither an
    exact-size raw buffer nor JPEG/PNG — callers choose skip vs fail.

    Payload rule (order matters): JPEG/PNG magic wins over the size
    heuristic — a compressed image whose byte length happens to equal
    H*W*3 must be decoded, not baked into the dataset as garbage
    "raw" pixels.
    """
    data, label = _payload_label(feats)
    return _decode_payload(data, label, image_size)


def _decode_payload(data, label, image_size):
    if data[:2] == _JPEG_MAGIC:
        # native libjpeg path when built (DCT-scaled decode + C resize,
        # GIL-free — recordio/jpeg.py).  The native decoder is strict;
        # anything it refuses (CMYK, warning-emitting streams) retries
        # through PIL inside decode_resized, so valid-but-odd images
        # still decode and corrupt ones still raise ValueError.
        from tensorflowonspark_tpu.recordio import jpeg as _jpeg

        return _jpeg.decode_resized(data, image_size), int(label)
    if data[:4] == _PNG_MAGIC:
        from PIL import Image  # host-side decode, one per record

        img = Image.open(io.BytesIO(data)).convert("RGB")
        if img.size != (image_size, image_size):
            img = img.resize((image_size, image_size), Image.BILINEAR)
        return np.asarray(img, np.uint8), int(label)
    raw = np.frombuffer(data, dtype=np.uint8)
    if raw.size == image_size * image_size * 3:
        return raw.reshape(image_size, image_size, 3), int(label)
    raise ValueError(
        f"image payload is {raw.size} bytes: neither "
        f"{image_size}x{image_size}x3 raw uint8 nor JPEG/PNG — check "
        f"--image_size against the dataset")


def decode_records_batch(recs, image_size, threads=None):
    """Decode an iterable of records → [(uint8 [S,S,3], int label)],
    routing all JPEG payloads through ONE threaded native decode
    (recordio.jpeg.decode_batch — the C call releases the GIL, so this
    scales with cores where the per-record loop cannot).  Raw and PNG
    records take the per-record path.  Error TYPES match
    ``decode_record``, but ordering differs: missing-field KeyErrors
    and bad non-JPEG payloads surface during the normalization pre-pass
    (before any JPEG is decoded), so with several bad records the one
    reported may not be the positionally first."""
    items = [_payload_label(f) for f in recs]
    out = [None] * len(items)
    jpeg_idx, jpeg_data = [], []
    for i, (data, label) in enumerate(items):
        if isinstance(data, (bytes, bytearray, memoryview)) \
                and bytes(data[:2]) == _JPEG_MAGIC:
            jpeg_idx.append(i)
            jpeg_data.append(bytes(data))
        else:
            out[i] = _decode_payload(data, label, image_size)
    if jpeg_idx:
        from tensorflowonspark_tpu.recordio import jpeg as _jpeg

        imgs = _jpeg.decode_batch(jpeg_data, image_size, threads=threads)
        for k, i in enumerate(jpeg_idx):
            out[i] = (imgs[k], int(items[i][1]))
    return out
