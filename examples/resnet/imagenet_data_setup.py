"""One-time ImageNet data prep: JPEG TFRecords -> raw uint8 TFRecords.

The reference trains from TF-official ImageNet TFRecords and decodes
JPEG inside tf.data's C++ threadpool; this framework's feeder tasks are
python processes where PIL decode is GIL-bound (~700 img/s measured —
far below the chip's appetite).  The TPU-shaped answer mirrors the
reference's mnist_data_setup pattern (reference
examples/mnist/mnist_data_setup.py:41-65): decode ONCE, in parallel
across engine executor processes (one task per shard), and train from
fixed-size raw uint8 records that feed at memory speed through the
columnar fast path.

    python examples/resnet/imagenet_data_setup.py \
        --input_dir /data/imagenet-jpeg-tfr --output_dir /data/imagenet-raw \
        --image_size 224 --num_executors 8

Input shards may use either layout this repo's loader understands:
TF-official ("image/encoded" JPEG/PNG bytes + "image/class/label",
1-based) or this repo's writers ("image" bytes + "label").  Output
shards are always ("image" raw uint8 HWC bytes, "label" 0-based int),
one output shard per input shard, written with the native TFRecord
codec — `resnet_imagenet_spark.py --data_dir <output_dir>` then skips
decode entirely.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def convert_shard(in_path, out_path, image_size):
    """Decode one input shard to fixed-size raw records (runs inside an
    executor task; returns (records, skipped)).  Record validity is
    decided by the SAME helper the training example uses
    (imagenet_records.decode_record); invalid records are skipped and
    counted, never silently written with default labels or raw-baked
    compressed bytes."""
    import imagenet_records

    from tensorflowonspark_tpu import recordio

    n = skipped = 0
    with recordio.TFRecordWriter(out_path) as w:
        for rec in recordio.TFRecordReader(in_path):
            # decode_example: {name: (kind, values)}
            feats = {k: v for k, (_kind, v)
                     in recordio.decode_example(rec).items()}
            try:
                arr, label = imagenet_records.decode_record(
                    feats, image_size)
            except (KeyError, ValueError) as e:
                if skipped < 3:
                    print(f"  skipping record in "
                          f"{os.path.basename(in_path)}: {e}", flush=True)
                skipped += 1
                continue
            w.write(recordio.encode_example({
                "image": ("bytes", [arr.tobytes()]),
                "label": ("int64", [int(label)]),
            }))
            n += 1
    return n, skipped


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--input_dir", required=True)
    p.add_argument("--output_dir", required=True)
    p.add_argument("--image_size", type=int, default=224)
    p.add_argument("--num_executors", type=int, default=4)
    args = p.parse_args()

    from tensorflowonspark_tpu.dfutil import _part_files
    from tensorflowonspark_tpu.engine import LocalEngine

    files = _part_files(args.input_dir)
    os.makedirs(args.output_dir, exist_ok=True)
    jobs = [(f, os.path.join(args.output_dir, os.path.basename(f)))
            for f in files]

    def run_partition(it):
        return [(os.path.basename(src),) + convert_shard(
            src, dst, args.image_size) for src, dst in it]

    try:  # under spark-submit: the real cluster does the decode
        from pyspark import SparkContext

        from tensorflowonspark_tpu.engine import SparkEngine

        engine = SparkEngine(SparkContext.getOrCreate())
    except ImportError:
        engine = LocalEngine(args.num_executors, env={"PYTHONPATH": ""})
    try:
        ds = engine.parallelize(jobs, min(len(jobs), args.num_executors * 2))
        results = ds.map_partitions(run_partition).collect()
    finally:
        engine.stop()
    total = sum(r[1] for r in results)
    skipped = sum(r[2] for r in results)
    for name, n, sk in sorted(results):
        print(f"  {name}: {n} records" + (f" ({sk} skipped)" if sk else ""))
    print(f"wrote {total} raw {args.image_size}px records in "
          f"{len(results)} shard(s) under {args.output_dir}"
          + (f"; skipped {skipped}" if skipped else ""))


if __name__ == "__main__":
    main()
