"""ResNet-50/ImageNet-224 via InputMode.SPARK ingestion — the literal
north-star workload (BASELINE.json config #3; reference shape:
examples/resnet/resnet_cifar_dist.py:144-148 scaled to ImageNet).

spark-submit (genuine Spark cluster):

    spark-submit --master $MASTER \\
        --conf spark.executor.instances=4 \\
        examples/resnet/resnet_imagenet_spark.py \\
        --cluster_size 4 --batch_size 1024 \\
        --data_dir hdfs:///imagenet/tfrecords --epochs 1

local engine (TPU VM / laptop, no Spark install):

    python examples/resnet/resnet_imagenet_spark.py \\
        --cluster_size 2 --batch_size 64 --steps 20   # synthetic data

The training loop is the framework's fast path: columnar shm-ring feed →
DataFeed → infeed.device_feed (double-buffered host→HBM staging) → a
donated, mesh-sharded jit train step; gradients all-reduce over ICI.

For JPEG TFRecords, run ``examples/resnet/imagenet_data_setup.py`` once
first: python-side PIL decode is GIL-bound (~700 img/s measured) and
would starve the chip, so the setup tool decodes in parallel across
engine executors into raw uint8 records this loop feeds at memory speed
(the in-loop decode below remains as a fallback for ad-hoc runs).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main_fun(args, ctx):
    import numpy as np
    import jax
    import optax

    from tensorflowonspark_tpu.infeed import device_feed, synchronized
    from tensorflowonspark_tpu.models import resnet
    from tensorflowonspark_tpu.parallel import (
        batch_sharding, local_to_global, make_mesh, shard_train_state,
    )
    from tensorflowonspark_tpu.utils import checkpoint as ckpt
    from tensorflowonspark_tpu.utils.metrics import TrainMetrics

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})
    image = args["image_size"]

    params, state = resnet.init(
        jax.random.PRNGKey(0), depth=50, num_classes=args["num_classes"]
    )
    opt = optax.sgd(args["lr"], momentum=0.9)
    opt_state = opt.init(params)

    ckpt_dir = os.path.join(args["model_dir"], "ckpt")
    restored, step = ckpt.restore_latest(ckpt_dir)
    if restored is not None:
        params, state = restored["params"], restored["state"]
        opt_state = ckpt.unpack_pytree(restored["opt"], opt_state)

    (params, state, opt_state), (p_sh, s_sh, o_sh) = shard_train_state(
        mesh, params, state, opt_state
    )
    step_fn = jax.jit(
        resnet.make_train_step(opt, depth=50,
                               accum_steps=args.get("accum_steps", 1)),
        in_shardings=(p_sh, s_sh, o_sh, batch_sharding(mesh),
                      batch_sharding(mesh)),
        out_shardings=(p_sh, s_sh, o_sh, None, None),
        donate_argnums=(0, 1, 2),
    )

    per_proc = args["batch_size"] // max(env["num_processes"], 1)
    metrics = TrainMetrics(
        flops_per_item=3 * resnet.flops_per_image(50, image)
    )
    feed = ctx.get_data_feed(
        train_mode=True, metrics=metrics,
        input_mapping={"image": "image", "label": "label"},
    )

    def collate(cols):
        # uint8 HWC records; normalization runs on device inside the
        # step.  Under columnar pull cols are already dense arrays, so
        # asarray + reshape are zero-copy views; with a row-path feeder
        # the same code degrades to one stack/copy.
        imgs = np.asarray(cols["image"], dtype=np.uint8).reshape(
            -1, image, image, 3
        )
        labels = np.asarray(cols["label"], dtype=np.int32)
        return imgs, labels

    def save(step):
        ckpt.save_checkpoint(
            ckpt_dir,
            {"params": params, "state": state,
             "opt": ckpt.pack_pytree(opt_state)},
            step,
        )

    loss = acc = 0.0
    # synchronized(): all processes stop on the same step at end of
    # feed even when ragged tails leave them different batch counts —
    # no stranded all-reduce, no reference-style "90% of steps" trick
    for imgs, labels in synchronized(device_feed(
        feed, per_proc, collate=collate, depth=2, columnar=True,
        placement=lambda b: local_to_global(mesh, b),
    ), feed=feed):
        params, state, opt_state, loss, acc = step_fn(
            params, state, opt_state, imgs, labels
        )
        step += 1
        metrics.step(len(labels) * env["num_processes"])
        if step % 20 == 0 and ctx.task_index == 0:
            r = metrics.report()
            print(f"step {step}: loss={float(loss):.4f} acc={float(acc):.3f} "
                  f"img/s={r.get('items_per_sec', 0):.0f} "
                  f"mfu={r.get('mfu', 0):.3f} "
                  f"stall={r['infeed_stall_frac']:.3f}", flush=True)
        if step % args["save_every"] == 0 and ckpt.is_chief(ctx):
            save(step)

    if ckpt.is_chief(ctx):
        save(step)
        r = metrics.report()
        print(f"final: step={step} img/s={r.get('items_per_sec', 0):.0f} "
              f"mfu={r.get('mfu', 0):.3f} stall={r['infeed_stall_frac']:.3f}",
              flush=True)


def _records(args, engine):
    """Training rows: ImageNet TFRecords (image/class bytes, dfutil
    schema) when --data_dir is given, else synthetic uint8 tensors."""
    import numpy as np

    if args.data_dir:
        from tensorflowonspark_tpu import dfutil

        ds, schema = dfutil.load_tfrecords(
            engine, args.data_dir,
            binary_features=("image", "image/encoded"),
            # stripe shard files across workers at the SOURCE: fewer
            # shards than workers must not starve feeds (synchronized
            # stop at step 0) nor fall into the record-level
            # repartition below, which materializes every encoded image
            # through the driver
            min_partitions=args.cluster_size,
        )
        image = args.image_size

        # ONE definition of record validity, shared with the prep tool
        # (imagenet_data_setup.py): raw uint8 "image"/"label" or
        # TF-official JPEG "image/encoded"/"image/class/label" (1-based)
        import imagenet_records

        if ds.num_partitions < args.cluster_size:
            # min_partitions striping should prevent this; keep a
            # belt-and-braces fallback for exotic sources.  Rebalances
            # the ENCODED records (before decode) but materializes them
            # through the driver on the local engine — load_tfrecords'
            # striping is the production path.
            print(f"WARNING: {ds.num_partitions} data partition(s) for "
                  f"{args.cluster_size} workers; repartitioning",
                  flush=True)
            ds = ds.repartition(args.cluster_size * 2)
        # stream the partition through the native threaded JPEG decoder
        # in bounded chunks: the batch call amortizes thread fan-out,
        # while chunking keeps peak memory at one chunk of encoded+
        # decoded records instead of the whole partition at once
        def decode_stream(it, chunk=256):
            batch = []
            for rec in it:
                batch.append(rec)
                if len(batch) >= chunk:
                    yield from imagenet_records.decode_records_batch(
                        batch, image)
                    batch = []
            if batch:
                yield from imagenet_records.decode_records_batch(
                    batch, image)

        return ds.map_partitions(decode_stream)
    rng = np.random.default_rng(0)
    n = args.batch_size * args.steps
    pool = [rng.integers(0, 256, (args.image_size, args.image_size, 3),
                         dtype=np.uint8) for _ in range(32)]
    rows = [(pool[i % len(pool)], int(i % args.num_classes))
            for i in range(n)]
    return engine.parallelize(rows, args.cluster_size * 2)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=256,
                   help="global batch (split across workers)")
    p.add_argument("--image_size", type=int, default=224)
    p.add_argument("--num_classes", type=int, default=1000)
    p.add_argument("--steps", type=int, default=20,
                   help="synthetic-data steps when --data_dir is absent")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--save_every", type=int, default=200)
    p.add_argument("--accum_steps", type=int, default=1,
                   help="gradient-accumulation microbatches per step "
                        "(effective batch beyond one chip's HBM)")
    p.add_argument("--data_dir", default=None,
                   help="TFRecord dir (file://, hdfs://, gs://)")
    p.add_argument("--model_dir", default="/tmp/resnet_imagenet")
    args = p.parse_args()

    from tensorflowonspark_tpu import cluster as TFCluster, configure_logging
    from tensorflowonspark_tpu.cluster import InputMode

    configure_logging()
    try:  # under spark-submit: federate the real Spark cluster
        from pyspark import SparkContext

        from tensorflowonspark_tpu.engine import SparkEngine

        engine = SparkEngine(SparkContext.getOrCreate())
    except ImportError:  # no Spark: the built-in executor pool
        from tensorflowonspark_tpu.engine import LocalEngine

        engine = LocalEngine(
            args.cluster_size,
            env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
                 "PYTHONPATH": "",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
        )

    cluster = TFCluster.run(
        engine, main_fun,
        {"batch_size": args.batch_size, "lr": args.lr,
         "image_size": args.image_size, "num_classes": args.num_classes,
         "model_dir": args.model_dir, "save_every": args.save_every,
         "accum_steps": args.accum_steps},
        num_executors=args.cluster_size, input_mode=InputMode.SPARK,
        master_node="chief",
    )
    cluster.train(_records(args, engine), num_epochs=args.epochs)
    cluster.shutdown(grace_secs=5)
    engine.stop()


if __name__ == "__main__":
    main()
