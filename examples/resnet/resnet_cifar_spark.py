"""ResNet/CIFAR-10 distributed training via engine feeding
(parity: reference examples/resnet/resnet_cifar_spark.py +
resnet_cifar_dist.py — the "<10 lines to port" story: the model/training
code is the plain single-process JAX from models/resnet.py; only the
main_fun wrapper and the cluster launch below are framework-specific).

    python examples/resnet/resnet_cifar_spark.py --cluster_size 2 \\
        --steps 10 --depth 20
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    import numpy as np
    import jax
    import optax

    from tensorflowonspark_tpu.models import resnet
    from tensorflowonspark_tpu.parallel import (
        batch_sharding, local_to_global, make_mesh, shard_train_state,
    )
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})

    params, state = resnet.init(
        jax.random.PRNGKey(0), depth=args["depth"], num_classes=10,
        width=16, small_inputs=True,
    )
    opt = optax.sgd(args["lr"], momentum=0.9)
    opt_state = opt.init(params)

    # resume-from-checkpoint (the framework's recovery contract: restart
    # the job, pick up params/BN-state/optimizer/step from the newest
    # checkpoint in model_dir)
    ckpt_dir = os.path.join(args["model_dir"], "ckpt")
    restored, start_step = ckpt.restore_latest(ckpt_dir)
    if restored is not None:
        params = restored["params"]
        state = restored["state"]
        opt_state = ckpt.unpack_pytree(restored["opt"], opt_state)

    (params, state, opt_state), (p_sh, s_sh, o_sh) = shard_train_state(
        mesh, params, state, opt_state
    )
    step_fn = jax.jit(
        resnet.make_train_step(opt, depth=args["depth"], small_inputs=True),
        in_shardings=(p_sh, s_sh, o_sh, batch_sharding(mesh),
                      batch_sharding(mesh)),
        out_shardings=(p_sh, s_sh, o_sh, None, None),
        donate_argnums=(0, 1, 2),
    )

    feed = ctx.get_data_feed(train_mode=True)
    per_proc = args["batch_size"] // max(env["num_processes"], 1)
    save_every = args.get("save_every", 25)

    def save(step):
        ckpt.save_checkpoint(
            ckpt_dir,
            {"params": params, "state": state,
             "opt": ckpt.pack_pytree(opt_state)},
            step,
        )

    step = start_step
    while not feed.should_stop():
        batch = feed.next_batch(per_proc)
        if len(batch) < per_proc:
            continue
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        labels = np.asarray([b[1] for b in batch], dtype=np.int32)
        gi, gl = local_to_global(mesh, (images, labels))
        params, state, opt_state, loss, acc = step_fn(
            params, state, opt_state, gi, gl
        )
        step += 1
        if step % 5 == 0 and ctx.task_index == 0:
            print(f"step {step}: loss={float(loss):.4f} acc={float(acc):.3f}")
        if step % save_every == 0 and ckpt.is_chief(ctx):
            save(step)

    if ckpt.is_chief(ctx):
        save(step)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--depth", type=int, default=20,
                   help="CIFAR plans: 20/32/44/56/110")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--model_dir", default="/tmp/resnet_cifar")
    args = p.parse_args()

    import numpy as np

    from tensorflowonspark_tpu import cluster as TFCluster, configure_logging
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine

    configure_logging()
    # synthetic CIFAR-shaped data (no egress in this environment)
    rng = np.random.default_rng(0)
    n = args.batch_size * args.steps
    images = rng.random((n, 32, 32, 3), dtype=np.float32)
    labels = (images.mean((1, 2, 3)) * 10).astype(np.int32) % 10
    records = list(zip(list(images), list(labels)))

    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": "",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    cluster = TFCluster.run(
        engine, main_fun,
        {"batch_size": args.batch_size, "lr": args.lr,
         "depth": args.depth, "model_dir": args.model_dir},
        num_executors=args.cluster_size, input_mode=InputMode.SPARK,
        master_node="chief",
    )
    cluster.train(engine.parallelize(records, args.cluster_size * 2),
                  num_epochs=args.epochs)
    cluster.shutdown(grace_secs=5)
    engine.stop()


if __name__ == "__main__":
    main()
