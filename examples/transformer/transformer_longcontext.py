"""Long-context transformer training on a dp x seq x model mesh — the
capability the reference never had (SURVEY.md §5 "Long-context: absent"):
ring-attention sequence parallelism splits the context across devices so
the per-device attention memory is O((S/n)^2) instead of O(S^2).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
        python examples/transformer/transformer_longcontext.py \\
        --seq_len 512 --steps 5
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--seq_len", type=int, default=512)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--n_layers", type=int, default=2)
    p.add_argument("--n_heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--attn", choices=["ring", "zigzag", "ulysses"],
                   default="ring")
    args = p.parse_args()

    import jax

    # a site hook may force the TPU platform at interpreter start; honor
    # an explicit JAX_PLATFORMS env (tests/conftest.py does the same)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.parallel import sequence_parallel_attention

    devs = jax.devices()
    n = len(devs)
    seq = max(n // 4, 1) * 2 if n >= 8 else max(n // 2, 1)
    model = 2 if n % 2 == 0 and n >= 4 else 1
    if args.attn == "ulysses":
        # ulysses re-shards seq->heads: the per-model-shard head count
        # must divide by the seq axis; the shrunk seq must also keep
        # dividing the device count (seq*model | n) or the mesh reshape
        # would fail
        while seq > 1 and ((args.n_heads // model) % seq
                           or n % (seq * model)):
            seq //= 2
    data = n // (seq * model)
    mesh = Mesh(np.array(devs).reshape(data, seq, model),
                ("data", "seq", "model"))
    print(f"mesh: {dict(mesh.shape)} for seq_len={args.seq_len}")

    cfg = transformer.Config(
        vocab_size=args.vocab, dim=args.dim, n_layers=args.n_layers,
        n_heads=args.n_heads, max_seq=args.seq_len, dtype="float32",
        attn_impl="reference",
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    specs = jax.tree.map(
        lambda s: NamedSharding(mesh, s), transformer.param_specs(cfg, mesh=mesh),
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.device_put(params, specs)
    opt = optax.adam(args.lr)
    opt_state = opt.init(params)
    if args.attn == "zigzag":
        # production zigzag: tokens are permuted ONCE per batch
        # (zigzag_lm_batch), rope positions and next-token labels are
        # explicit, and the loss runs directly on the permuted layout —
        # no per-layer gathers; the causal ring's critical path halves
        from tensorflowonspark_tpu.parallel import zigzag_permutation

        attn_fn = sequence_parallel_attention(mesh, "zigzag", causal=True)
        zz_perm = zigzag_permutation(args.seq_len, mesh.shape["seq"])
    else:
        attn_fn = sequence_parallel_attention(mesh, args.attn, causal=True)
        zz_perm = None

    @jax.jit
    def step(params, opt_state, tokens):
        toks, labels, positions = (
            transformer.zigzag_lm_batch(tokens, zz_perm)
            if zz_perm is not None else (tokens, None, None)
        )
        loss, grads = jax.value_and_grad(transformer.loss_fn)(
            params, toks, cfg, attn_fn=attn_fn, labels=labels,
            positions=positions,
        )
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    # token stream: next token = (2*prev + 1) % vocab — learnable pattern
    rng = np.random.default_rng(0)
    tok_sh = NamedSharding(mesh, P("data", "seq"))
    for i in range(1, args.steps + 1):
        start = rng.integers(0, args.vocab, (args.batch_size, 1))
        toks = [start]
        for _ in range(args.seq_len - 1):
            toks.append((2 * toks[-1] + 1) % args.vocab)
        tokens = jax.device_put(
            jnp.asarray(np.concatenate(toks, axis=1), jnp.int32), tok_sh
        )
        params, opt_state, loss = step(params, opt_state, tokens)
        print(f"step {i}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
