"""Reshape a flat-784 MNIST CSV row into a 28x28 ASCII preview
(parity: reference examples/utils/mnist_reshape.py — a 9-line inference
debugging helper).

    python examples/utils/mnist_reshape.py "0,0,...,255"
"""

import sys

import numpy as np


def reshape(csv_row):
    vals = np.fromstring(csv_row, dtype=np.float32, sep=",")
    pixels = vals[1:] if len(vals) == 785 else vals
    img = pixels.reshape(28, 28)
    scale = " .:-=+*#%@"
    lines = [
        "".join(scale[min(int(v / 256.0 * len(scale)), len(scale) - 1)]
                for v in row)
        for row in img
    ]
    return "\n".join(lines)


if __name__ == "__main__":
    print(reshape(sys.argv[1]))
