"""Gracefully stop a streaming cluster from outside the driver.

Sends STOP to the cluster's rendezvous server, which makes the driver's
``train_stream`` loop end after the in-flight micro-batch (parity:
reference examples/utils/stop_streaming.py:16, which uses
reservation.Client the same way).

Usage:
    python stop_streaming.py <host> <port>
"""

import argparse

from tensorflowonspark_tpu import rendezvous


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("host", help="rendezvous server host")
    parser.add_argument("port", type=int, help="rendezvous server port")
    ns = parser.parse_args()

    client = rendezvous.Client((ns.host, ns.port))
    client.request_stop()
    client.close()
    print(f"sent STOP to {ns.host}:{ns.port}")


if __name__ == "__main__":
    main()
