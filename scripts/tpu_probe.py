"""Tiny TPU liveness probe for the perf session: claim the backend,
run one small matmul, print device + timing, exit.  A hang or error
here (bounded by the caller's timeout, default 5 min) means the
tunnel/pool is sick — better to learn that up front than 25 minutes
into the first ResNet compile (the round-3 failure mode).

Exit codes: 0 healthy; 2 backend is CPU (no TPU behind the tunnel);
3 device returned a wrong result; 4 relay port closed (diagnosed
pre-jax: with the axon site hook present, `import jax` HANGS on a dead
tunnel, so without this check a dead relay costs the caller's full
probe timeout instead of ~2 s).
"""

import json
import os
import subprocess
import sys
import time


def _relay_port_dead():
    """True when we are headed for the axon backend but its loopback
    relay refuses connections (terminal: nothing in the VM restarts it).
    Skipped when JAX_PLATFORMS pins a non-axon backend (CPU smoke)."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms and "axon" not in platforms:
        return False
    port = os.environ.get("TFOS_RELAY_PORT", "8082")
    rc = subprocess.call(
        ["timeout", "2", "bash", "-c", f"echo > /dev/tcp/127.0.0.1/{port}"],
        stderr=subprocess.DEVNULL)
    return rc != 0


def main():
    if _relay_port_dead():
        print("probe: axon relay port refused - tunnel is dead "
              "(import jax would hang)", file=sys.stderr, flush=True)
        raise SystemExit(4)
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    t_backend = time.perf_counter() - t0

    t0 = time.perf_counter()
    x = jnp.ones((256, 256), jnp.bfloat16)
    y = (x @ x).sum()
    val = float(y)
    t_compute = time.perf_counter() - t0

    result_ok = abs(val - 256 * 256 * 256) < 1e-3 * 256 ** 3
    print(json.dumps({
        "device": str(dev),
        "kind": getattr(dev, "device_kind", "?"),
        "platform": dev.platform,
        "backend_init_s": round(t_backend, 1),
        "first_compute_s": round(t_compute, 1),
        "result_ok": result_ok,
    }), flush=True)
    if dev.platform == "cpu":
        print("probe: backend is CPU - no TPU behind the tunnel",
              file=sys.stderr, flush=True)
        raise SystemExit(2)
    if not result_ok:
        print(f"probe: device returned wrong result ({val})",
              file=sys.stderr, flush=True)
        raise SystemExit(3)


if __name__ == "__main__":
    main()
