"""Tiny TPU liveness probe for the perf session: claim the backend,
run one small matmul, print device + timing, exit.  A hang or error
here (bounded by the caller's timeout, default 5 min) means the
tunnel/pool is sick — better to learn that up front than 25 minutes
into the first ResNet compile (the round-3 failure mode).

Exit codes: 0 healthy; 2 backend is CPU (no TPU behind the tunnel);
3 device returned a wrong result.
"""

import json
import sys
import time


def main():
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    t_backend = time.perf_counter() - t0

    t0 = time.perf_counter()
    x = jnp.ones((256, 256), jnp.bfloat16)
    y = (x @ x).sum()
    val = float(y)
    t_compute = time.perf_counter() - t0

    result_ok = abs(val - 256 * 256 * 256) < 1e-3 * 256 ** 3
    print(json.dumps({
        "device": str(dev),
        "kind": getattr(dev, "device_kind", "?"),
        "platform": dev.platform,
        "backend_init_s": round(t_backend, 1),
        "first_compute_s": round(t_compute, 1),
        "result_ok": result_ok,
    }), flush=True)
    if dev.platform == "cpu":
        print("probe: backend is CPU - no TPU behind the tunnel",
              file=sys.stderr, flush=True)
        raise SystemExit(2)
    if not result_ok:
        print(f"probe: device returned wrong result ({val})",
              file=sys.stderr, flush=True)
        raise SystemExit(3)


if __name__ == "__main__":
    main()
