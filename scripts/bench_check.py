#!/usr/bin/env python
"""Perf-regression gate over the repo's ``BENCH_*.json`` lines.

Diffs the newest usable bench line against the prior round, lane by
lane (ResNet img/s, transformer tok/s, fed img/s, data rec/s, serve
p99, decode tokens/s + p99s, ...), and exits non-zero when any lane
regressed past the
tolerance — the CI-shaped check the session scripts run after a bench
step so a perf cliff is a red line in the log, not an archaeology
project (PERF.md history stays the narrative; this is the gate).

Bench files come in two shapes and both are handled:

- bare bench lines (``BENCH_session_*.json``): the one-JSON-line
  ``{"metric", "value", "unit", "extra": {lanes...}}`` record bench.py
  prints;
- driver wrappers (``BENCH_r0N.json``): ``{"n", "cmd", "rc", "tail",
  "parsed"}`` where ``parsed`` (or the last JSON object line of
  ``tail``) is the bench line.

Fail-safe lines (``"value": null`` + ``extra.error`` — dead-tunnel
rounds) carry no lane numbers and are skipped, so the gate compares
the two most recent rounds that actually measured something.  Lanes
disabled in one round (``TFOS_BENCH_*=0``) are simply absent and not
compared — only lanes present on BOTH sides count.

Exit codes: 0 OK / skip (nothing comparable), 1 regression,
2 usage error.

Usage::

    python scripts/bench_check.py [--dir REPO] [--tolerance 0.10]
    python scripts/bench_check.py --baseline OLD.json --latest NEW.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

TOL_ENV = "TFOS_BENCH_TOL"

# (lane label, path into the bench line, higher_is_better).
# ("value",) is the headline metric (ResNet train MFU).  NOTE: bench
# lines before round 4 counted ResNet FLOPs as GMacs (exactly half the
# 2-FLOPs/MAC convention) — mfu comparisons across that boundary are
# apples-to-oranges; throughput lanes never changed convention.
LANES = (
    ("resnet.mfu", ("value",), True),
    ("resnet.img_s", ("extra", "images_per_sec_per_chip"), True),
    ("transformer.tok_s",
     ("extra", "transformer", "tokens_per_sec_per_chip"), True),
    ("fed.img_s", ("extra", "fed", "images_per_sec_per_chip"), True),
    ("data.raw_rec_s", ("extra", "data", "raw_records_per_sec"), True),
    ("data.pipeline_rec_s",
     ("extra", "data", "pipeline_records_per_sec"), True),
    ("data.service_rec_s",
     ("extra", "data", "service_records_per_sec"), True),
    ("data.dynamic_rec_s",
     ("extra", "data", "dynamic_records_per_sec"), True),
    ("data.straggler_speedup",
     ("extra", "data", "straggler_speedup"), True),
    ("data.cache_hit_rec_s",
     ("extra", "data", "cache_hit_records_per_sec"), True),
    ("tfrecord.columnar_rec_s",
     ("extra", "tfrecord_read", "columnar_records_per_sec"), True),
    ("serve.req_s", ("extra", "serve", "req_per_sec"), True),
    ("serve.p99_ms", ("extra", "serve", "p99_ms"), False),
    ("decode.tok_s", ("extra", "decode", "tokens_per_sec"), True),
    ("decode.ttft_p50_ms", ("extra", "decode", "ttft_p50_ms"), False),
    ("decode.ttft_p99_ms", ("extra", "decode", "ttft_p99_ms"), False),
    ("decode.tok_p99_ms", ("extra", "decode", "tok_p99_ms"), False),
    ("decode.prefix_hit_rate",
     ("extra", "decode", "prefix_hit_rate"), True),
    ("decode.prefill_tok_saved",
     ("extra", "decode", "prefill_tokens_saved"), True),
    ("fabric.req_s", ("extra", "serve_fabric", "req_per_sec"), True),
    ("fabric.p99_ms", ("extra", "serve_fabric", "p99_ms"), False),
    ("fabric.dropped", ("extra", "serve_fabric", "dropped"), False),
    ("fabric.affinity_hit_rate",
     ("extra", "serve_fabric", "affinity_hit_rate"), True),
    ("fabric.scale_ups", ("extra", "serve_fabric", "scale_ups"), True),
    ("elastic.resize_ms", ("extra", "elastic", "resize_ms"), False),
    ("elastic.reshard_ms", ("extra", "elastic", "reshard_ms"), False),
    ("elastic_serve.resize_ms",
     ("extra", "elastic_serve", "resize_ms"), False),
    ("elastic_serve.degraded_p99_ms",
     ("extra", "elastic_serve", "degraded_p99_ms"), False),
    ("elastic_serve.dropped", ("extra", "elastic_serve", "dropped"), False),
    ("deploy.promote_s", ("extra", "deploy", "promote_s"), False),
    ("deploy.rollback_s", ("extra", "deploy", "rollback_s"), False),
    ("deploy.p99_ms", ("extra", "deploy", "p99_ms"), False),
    ("deploy.dropped", ("extra", "deploy", "dropped"), False),
    ("actors.ask_p50_ms", ("extra", "actors", "ask_p50_ms"), False),
    ("actors.ask_p99_ms", ("extra", "actors", "ask_p99_ms"), False),
    ("actors.respawn_resume_ms",
     ("extra", "actors", "respawn_resume_ms"), False),
)

# Absolute floors, checked on the NEWEST line alone (no baseline
# needed): lanes whose meaning is a contract, not a trend.  A
# straggler_speedup near 1.0 means dispatch regressed to static-shard
# behavior — that must fail even if the prior round was just as bad.
# fabric.scale_ups < 1 means the autoscaler provably never scaled under
# the lane's induced queueing; a zero affinity_hit_rate means session
# routing stopped landing returning sessions on their bound replica.
FLOORS = {
    "data.straggler_speedup": 1.2,
    "fabric.scale_ups": 1.0,
    "fabric.affinity_hit_rate": 0.001,
}

# Absolute ceilings, the floors' mirror: fabric.dropped is the fabric
# lane's zero-drop contract (client-visible errors across the mid-run
# SIGKILL), pinned at 0 regardless of what the prior round did.
CEILINGS = {
    "fabric.dropped": 0.0,
}

# Contract lanes whose round-over-round trend is meaningless (how MANY
# times the autoscaler stepped is load-shape, not performance): gated
# by FLOORS/CEILINGS above, excluded from the relative comparison.
FLOOR_ONLY = frozenset({"fabric.scale_ups", "fabric.affinity_hit_rate"})


def _dig(obj, path):
    for p in path:
        if not isinstance(obj, dict) or p not in obj:
            return None
        obj = obj[p]
    if isinstance(obj, bool) or not isinstance(obj, (int, float)):
        return None
    return float(obj)


def extract_line(doc):
    """The bench line dict from either file shape, or None."""
    if not isinstance(doc, dict):
        return None
    if "metric" in doc:
        return doc
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and "metric" in cand:
                return cand
    return None


def lanes_of(line):
    """{lane label: value} for every lane the line carries."""
    out = {}
    for label, path, _hib in LANES:
        v = _dig(line, path)
        if v is not None:
            out[label] = v
    return out


def load_bench(path):
    """(lanes dict, bench line) for one file; ({}, None) if unusable."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}, None
    line = extract_line(doc)
    if line is None:
        return {}, None
    return lanes_of(line), line


def discover(bench_dir):
    """Usable bench files, oldest -> newest.  Ordered by mtime with the
    filename as tiebreak (checkout-restored files share one mtime;
    BENCH_r01 < ... < BENCH_session_* sorts rounds correctly there)."""
    paths = sorted(glob.glob(os.path.join(bench_dir, "BENCH_*.json")),
                   key=lambda p: (os.path.getmtime(p), p))
    out = []
    for p in paths:
        lanes, line = load_bench(p)
        if lanes:
            out.append((p, lanes))
    return out


def compare(old_lanes, new_lanes, tolerance):
    """[(label, old, new, rel_change, regressed)] over shared lanes."""
    rows = []
    for label, _path, hib in LANES:
        if label in FLOOR_ONLY:
            continue
        if label not in old_lanes or label not in new_lanes:
            continue
        old, new = old_lanes[label], new_lanes[label]
        if old <= 0:
            # zero is a meaningful floor for lower-is-better lanes
            # (elastic_serve.dropped: the zero-drop contract) — any
            # departure from it regresses; ratios are undefined, so
            # report the absolute delta as the change
            if old == 0 and not hib:
                rows.append((label, old, new, new, new > tolerance))
            continue
        rel = (new - old) / old
        regressed = (rel < -tolerance) if hib else (rel > tolerance)
        rows.append((label, old, new, rel, regressed))
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="directory holding BENCH_*.json (default: the "
                         "repo root above this script)")
    ap.add_argument("--tolerance", type=float,
                    default=float(os.environ.get(TOL_ENV, "0.10")),
                    help="allowed fractional regression per lane "
                         f"(default 0.10; env {TOL_ENV})")
    ap.add_argument("--baseline", default=None,
                    help="explicit prior bench file (skips discovery)")
    ap.add_argument("--latest", default=None,
                    help="explicit newest bench file (skips discovery)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-lane table (verdict only)")
    args = ap.parse_args(argv)

    if bool(args.baseline) != bool(args.latest):
        ap.error("--baseline and --latest must be given together")
    if args.baseline:
        old_path, new_path = args.baseline, args.latest
        old_lanes, _ = load_bench(old_path)
        new_lanes, _ = load_bench(new_path)
        if not new_lanes or not old_lanes:
            print("bench_check: ERROR unusable bench file "
                  f"({old_path if not old_lanes else new_path})")
            return 2
    else:
        bench_dir = args.dir or os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        usable = discover(bench_dir)
        if len(usable) < 2:
            print(f"bench_check: SKIP ({len(usable)} usable BENCH line(s) "
                  f"under {bench_dir}; need 2 to compare)")
            return 0
        (old_path, old_lanes), (new_path, new_lanes) = usable[-2], usable[-1]

    floor_bad = [(label, new_lanes[label], floor)
                 for label, floor in sorted(FLOORS.items())
                 if label in new_lanes and new_lanes[label] < floor]
    for label, value, floor in floor_bad:
        print(f"  {label:<24} {value:>12.2f} below floor {floor:.2f}  "
              f"REGRESSED")
    ceil_bad = [(label, new_lanes[label], ceil)
                for label, ceil in sorted(CEILINGS.items())
                if label in new_lanes and new_lanes[label] > ceil]
    for label, value, ceil in ceil_bad:
        print(f"  {label:<24} {value:>12.2f} above ceiling {ceil:.2f}  "
              f"REGRESSED")
    floor_bad += ceil_bad
    rows = compare(old_lanes, new_lanes, args.tolerance)
    if not rows and not floor_bad:
        print("bench_check: SKIP (no lane present in both "
              f"{os.path.basename(old_path)} and "
              f"{os.path.basename(new_path)})")
        return 0
    if not args.quiet:
        for label, old, new, rel, regressed in rows:
            flag = "REGRESSED" if regressed else "ok"
            print(f"  {label:<24} {old:>12.2f} -> {new:>12.2f} "
                  f"{rel:>+7.1%}  {flag}")
    bad = [r for r in rows if r[4]]
    names = (os.path.basename(new_path), os.path.basename(old_path))
    if floor_bad:
        label, value, bound = floor_bad[0]
        print(f"bench_check: REGRESSION {label} {value:.2f} outside "
              f"absolute bound {bound:.2f} newest={names[0]} "
              f"[{len(floor_bad)} floor/ceiling violation(s), "
              f"{len(bad)}/{len(rows)} lanes regressed]")
        return 1
    if bad:
        worst = max(bad, key=lambda r: abs(r[3]))
        print(f"bench_check: REGRESSION {worst[0]} {worst[3]:+.1%} "
              f"({worst[1]:.2f} -> {worst[2]:.2f}, tol "
              f"{args.tolerance:.0%}) newest={names[0]} prior={names[1]} "
              f"[{len(bad)}/{len(rows)} lanes regressed]")
        return 1
    worst = min(rows, key=lambda r: r[3] if r[4] is False else 0)
    print(f"bench_check: OK newest={names[0]} prior={names[1]} "
          f"lanes={len(rows)} worst={worst[0]} {worst[3]:+.1%} "
          f"(tol {args.tolerance:.0%})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
