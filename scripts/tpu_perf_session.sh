#!/bin/bash
# One-command on-chip perf session (PERF.md's plan, in order):
#
#   1. ResNet-50 sweep (stem x batch x remat x bn-fusion), promote
#   2. Profile the winning config -> PERF_BREAKDOWN.md (where time goes)
#   3. Transformer sweep (batch x flash blocks x remat x bwd x CE), promote
#   4. Run bench.py with the promoted configs -> the round's JSON line
#
# Each step is its own process (the tunnel serializes TPU claims) under
# scripts/with_tunnel_watchdog.sh via _session_lib.sh: a step is killed
# within ~1 min of the relay dying (session aborts - a dead relay is
# terminal) and bounded by a per-step timeout (a timed-out step logs
# and the session continues: partial results beat none).  Scripts print
# nothing for many minutes during big compiles, which is normal
# (see CLAUDE.md).
set -uo pipefail
cd "$(dirname "$0")/.."

if ! timeout 3 bash -c "echo > /dev/tcp/127.0.0.1/8082" 2>/dev/null; then
  echo "WARNING: axon relay port 8082 closed - the TPU tunnel looks down" >&2
fi

log=${TFOS_PERF_LOG:-perf_session.log}
echo "== tpu perf session $(date -u +%FT%TZ) ==" | tee -a "$log"
source scripts/_session_lib.sh

# persistent XLA compilation cache shared across the session's processes:
# the winning config is compiled by the sweep, then AGAIN by profile,
# bench, and the fed lane — each a multi-minute first-compile through the
# tunnel.  The disk cache turns the repeats into loads.
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/tfos_xla_cache}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

# TFOS_SESSION_SMOKE=1: CPU dry run of the WHOLE session pipeline (tiny
# shapes, promote refused by the sweeps, bench skipped, watchdog port
# check off) so script bugs surface here, not in the first minutes of a
# live chip claim.
profile_extra=""
if [ "${TFOS_SESSION_SMOKE:-0}" = "1" ]; then
  export TFOS_SWEEP_SMOKE=1
  profile_extra="--batch 4"
  echo "(smoke mode: tiny shapes, no promote, bench skipped)" | tee -a "$log"
else
  probe_gate
fi

session_run 7200 python scripts/sweep_resnet.py \
    --steps "${TFOS_SESSION_RESNET_STEPS:-20}" \
    --image "${TFOS_SESSION_IMAGE:-224}" --promote
# promoted-config args come first so $profile_extra (smoke mode's
# --batch 4) wins argparse's last-takes-effect — a CPU dry run must
# never profile at a previously promoted TPU batch size
session_run 3600 python scripts/profile_resnet.py \
    --out "${TFOS_SESSION_BREAKDOWN:-PERF_BREAKDOWN.md}" \
    --steps "${TFOS_SESSION_RESNET_STEPS:-10}" \
    --image "${TFOS_SESSION_IMAGE:-224}" \
    $(python scripts/promoted_profile_args.py) \
    $profile_extra
session_run 7200 python scripts/sweep_transformer.py \
    --steps "${TFOS_SESSION_TRANSFORMER_STEPS:-8}" --promote
# host-side fed-consumer ceiling (no TPU claim: feeder+DataFeed only) —
# the number that bounds fed training throughput on THIS host
if [ "${TFOS_SESSION_STRESS:-1}" = "1" ] && [ "${TFOS_SESSION_SMOKE:-0}" != "1" ]; then
  host_run 1800 python scripts/stress_fed.py --batch 256 --image 224 --steps 24
fi
if [ "${TFOS_SESSION_SMOKE:-0}" = "1" ]; then
  echo "-- bench.py skipped (smoke mode) --" | tee -a "$log"
else
  # serve + decode lanes run host-side on CPU-forced replicas (never a
  # second TPU claim); TFOS_BENCH_SERVE=0 / TFOS_BENCH_DECODE=0 skip
  # them if the host is too loaded for meaningful latency percentiles
  # watchtower on in observe-only mode: the bench line's "health" block
  # records anomalies (NaN, spikes, stalls) seen during the lanes, but
  # never halts an unattended TPU round (docs/observability.md)
  TFOS_BENCH_SERVE="${TFOS_BENCH_SERVE:-1}" \
  TFOS_BENCH_ELASTIC_SERVE="${TFOS_BENCH_ELASTIC_SERVE:-1}" \
  TFOS_BENCH_DECODE="${TFOS_BENCH_DECODE:-1}" \
  TFOS_BENCH_DECODE_PREFIX="${TFOS_BENCH_DECODE_PREFIX:-0.6}" \
  TFOS_HEALTH_ACTION="${TFOS_HEALTH_ACTION:-none}" \
  TFOS_HEALTH_GRADNORM="${TFOS_HEALTH_GRADNORM:-0}" \
    session_run 7200 python bench.py
fi
# perf-regression gate: newest BENCH line vs prior round (host-side,
# no TPU claim; host_run never aborts the session on a red verdict)
host_run 120 python scripts/bench_check.py

echo "== done; promoted config: ==" | tee -a "$log"
cat "${TFOS_BENCH_CONFIG:-bench_config.json}" 2>/dev/null | tee -a "$log" || \
  echo "(no bench_config.json written)" | tee -a "$log"
