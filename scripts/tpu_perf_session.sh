#!/bin/bash
# One-command on-chip perf session (PERF.md's plan, in order):
#
#   1. ResNet-50 sweep (stem x batch x remat), promote the winner
#   2. Profile the winning config -> PERF_BREAKDOWN.md (where time goes)
#   3. Transformer sweep (batch x flash blocks x remat x bwd), promote
#   4. Run bench.py with the promoted configs -> the round's JSON line
#
# Each step is its own process (the tunnel serializes TPU claims); a
# step failing does not stop the later ones — partial results beat none.
# Check tunnel liveness first: scripts print nothing for many minutes
# during big compiles, which is normal (see CLAUDE.md).
set -uo pipefail
cd "$(dirname "$0")/.."

if ! timeout 3 bash -c "echo > /dev/tcp/127.0.0.1/8082" 2>/dev/null; then
  echo "WARNING: axon relay port 8082 closed - the TPU tunnel looks down" >&2
fi

log=${TFOS_PERF_LOG:-perf_session.log}
echo "== tpu perf session $(date -u +%FT%TZ) ==" | tee -a "$log"

run() {
  echo "-- $* --" | tee -a "$log"
  "$@" 2>&1 | tee -a "$log"
  echo "-- rc=$? --" | tee -a "$log"
}

run python scripts/sweep_resnet.py --steps "${TFOS_SESSION_RESNET_STEPS:-20}" --image "${TFOS_SESSION_IMAGE:-224}" --promote
run python scripts/profile_resnet.py --out PERF_BREAKDOWN.md \
    --steps "${TFOS_SESSION_RESNET_STEPS:-10}" --image "${TFOS_SESSION_IMAGE:-224}" \
    $(python - <<'EOF'
import json, os
cfg = {}
if os.path.exists("bench_config.json"):
    try:
        cfg = json.load(open("bench_config.json"))
    except ValueError:
        pass
args = []
if cfg.get("batch"):
    args += ["--batch", str(cfg["batch"])]
if not cfg.get("stem_s2d", True):
    args += ["--stem", "7x7"]
if cfg.get("remat"):
    args += ["--remat"]
print(" ".join(args))
EOF
)
run python scripts/sweep_transformer.py --steps "${TFOS_SESSION_TRANSFORMER_STEPS:-8}" --promote
run python bench.py

echo "== done; promoted config: ==" | tee -a "$log"
cat bench_config.json 2>/dev/null | tee -a "$log" || \
  echo "(no bench_config.json written)" | tee -a "$log"
