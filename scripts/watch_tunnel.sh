#!/bin/bash
# Poll the axon relay port; when it opens, fire the given command
# (default: the round-4 follow-up session).  Round-3 lesson: a tunnel
# that comes back mid-session must never be missed.  Round-4 lesson:
# the session can ABORT early (probe rc 2/3/4, relay death rc 86) and
# the tunnel can come back AGAIN later — re-arm after failures, exit
# only when the session completes.
#   bash scripts/watch_tunnel.sh [cmd...]
set -u
cd "$(dirname "$0")/.."
cmd=("${@:-}")
if [ -z "${cmd[0]:-}" ]; then cmd=(bash scripts/tpu_round4_followup.sh); fi
echo "watching port 8082 for the tunnel; will run: ${cmd[*]}"
fails=0
while true; do
  if timeout 2 bash -c "echo > /dev/tcp/127.0.0.1/8082" 2>/dev/null; then
    echo "tunnel OPEN at $(date -u +%FT%TZ); firing"
    "${cmd[@]}"
    rc=$?
    if [ "$rc" = "0" ]; then
      echo "session completed rc=0 at $(date -u +%FT%TZ); watcher done"
      exit 0
    fi
    if [ "$rc" = "126" ] || [ "$rc" = "127" ] || [ "$rc" = "130" ]; then
      # broken harness / operator interrupt: deterministic, retrying
      # would re-claim the chip every cycle for the same failure
      echo "session failed rc=$rc (harness/interrupt) at $(date -u +%FT%TZ); NOT re-arming" 
      exit "$rc"
    fi
    # aborted (sick pool / relay died mid-run / probe hang 124|137):
    # wait out the flap, then re-arm — an open-but-sick port must not
    # hot-loop the session.  Capped: a DETERMINISTIC failure with a
    # healthy port (e.g. a reproducible step crash exiting rc=1) would
    # otherwise re-claim the chip every cycle forever.
    fails=$((fails + 1))
    if [ "$fails" -ge 5 ]; then
      echo "session aborted rc=$rc; $fails consecutive failures -" \
           "giving up (not a tunnel flap)"
      exit "$rc"
    fi
    echo "session aborted rc=$rc at $(date -u +%FT%TZ); re-arming in 120s" \
         "(attempt $fails/5)"
    sleep 120
  else
    sleep 30
  fi
done
