#!/bin/bash
# Poll the axon relay port; the moment it opens, fire the given command
# (default: the round-4 follow-up session).  Round-3 lesson: a tunnel
# that comes back mid-session must never be missed.
#   bash scripts/watch_tunnel.sh [cmd...]
set -u
cd "$(dirname "$0")/.."
cmd=("${@:-}")
if [ -z "${cmd[0]:-}" ]; then cmd=(bash scripts/tpu_round4_followup.sh); fi
echo "watching port 8082 for the tunnel; will run: ${cmd[*]}"
while true; do
  if timeout 2 bash -c "echo > /dev/tcp/127.0.0.1/8082" 2>/dev/null; then
    echo "tunnel OPEN at $(date -u +%FT%TZ); firing"
    "${cmd[@]}"
    exit $?
  fi
  sleep 30
done
