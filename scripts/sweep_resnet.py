"""One-process ResNet-50 perf sweep: measures several configurations under
a single TPU claim (the tunnel serializes claims, so N processes would pay
N claim round-trips).

Sweeps: stem (s2d vs 7x7), batch size, remat, and the BatchNorm backward
(custom-VJP fused vs plain autodiff); prints one line per config and a
final ranking.  Use TFOS_SWEEP=b256_s2d_bnf,b512_s2d_bnf,... to subset
by the names in CONFIGS below.

Usage: python scripts/sweep_resnet.py [--steps 10]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# (name, batch, stem_s2d, remat, bn_fused) — most promising first, so a
# flaky tunnel session still yields the configs that matter.  Module-level
# so dry-run tests can substitute tiny shapes while driving the REAL
# sweep/promote/refusal paths.  bn_fused: custom-VJP BatchNorm backward
# (two fused HBM passes; see models/layers._bn_train_fused) vs plain
# autodiff — the round-4 profile showed ~38% of the step in unfused BN
# backward multiplies.
CONFIGS = [
    ("b256_s2d_bnf", 256, True, False, True),
    ("b512_s2d_bnf", 512, True, False, True),
    ("b384_s2d_bnf", 384, True, False, True),
    ("b256_s2d", 256, True, False, False),
    ("b512_s2d_remat_bnf", 512, True, True, True),
    ("b256_7x7_bnf", 256, False, False, True),
    # r5 structural probes: r4 measured per-image throughput FALLING
    # with batch (256: 2581, 384: 2494, 512: 2444 img/s) on an
    # HBM-bound step — if capacity pressure (spills/copies) is the
    # cause, SMALLER batches should run faster per image; and remat,
    # which lost 25% with autodiff BN, re-enters with the fused-BN
    # backward's cheaper recompute.
    ("b128_s2d_bnf", 128, True, False, True),
    ("b192_s2d_bnf", 192, True, False, True),
    ("b256_s2d_remat_bnf", 256, True, True, True),
]


def config_path():
    """bench_config.json location — resolved by bench.bench_config_path
    (the single source of truth; TFOS_BENCH_CONFIG overrides)."""
    import bench

    return bench.bench_config_path()


def measure(step_fn, params, state, opt_state, images, labels, steps):
    import jax
    from jax import lax

    @jax.jit
    def run(params, state, opt_state, images, labels):
        def body(carry, _):
            p, s, o = carry
            p, s, o, loss, _ = step_fn(p, s, o, images, labels)
            return (p, s, o), loss
        (_, _, _), losses = lax.scan(
            body, (params, state, opt_state), None, length=steps)
        return losses[-1]
    return _timed(run, params, state, opt_state, images, labels, steps)


def measure_decomposed(mode, opt, cfg_kwargs, params, state, opt_state,
                       images, labels, steps):
    """TFOS_SWEEP_MODE=fwd|grad step-time decomposition (no promote):
    'fwd' scans the forward loss only; 'grad' scans value_and_grad but
    skips the optimizer update.  train - grad = optimizer cost;
    grad - fwd = backward cost.  One chip claim, no profiler."""
    import jax
    from jax import lax

    from tensorflowonspark_tpu.models import resnet

    def loss_fn(p, s, x, y):
        logits, new_s = resnet.apply(
            p, s, x, depth=50, train=True,
            compute_dtype=jax.numpy.bfloat16,
            stem_s2d=cfg_kwargs["stem_s2d"], bn_fused=cfg_kwargs["bn_fused"])
        from tensorflowonspark_tpu.models import layers as L
        return L.softmax_cross_entropy(logits, y), new_s

    if mode == "fwd":
        @jax.jit
        def run(params, state, opt_state, images, labels):
            # the loss must depend on the carry or XLA's while-loop
            # invariant code motion hoists the whole forward out of the
            # scan (train-mode BN reads only params/images).  eps is a
            # zero-valued scalar chained through the previous loss —
            # value-neutral, but it serializes the iterations.
            def body(carry, _):
                s, eps = carry
                loss, new_s = loss_fn(params, s, images + eps, labels)
                return (new_s, (0.0 * loss).astype(images.dtype)), loss
            zero = jax.numpy.zeros((), images.dtype)
            _, losses = lax.scan(body, (state, zero), None, length=steps)
            return losses[-1]
    else:  # grad
        @jax.jit
        def run(params, state, opt_state, images, labels):
            def body(carry, _):
                p, s = carry
                (loss, new_s), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(p, s, images, labels)
                # consume grads without an optimizer: fold a zero-scaled
                # update into the carry so XLA cannot DCE the backward
                p = jax.tree.map(lambda a, g: a - 0.0 * g, p, grads)
                return (p, new_s), loss
            _, losses = lax.scan(body, (params, state), None, length=steps)
            return losses[-1]
    return _timed(run, params, state, opt_state, images, labels, steps)


def _timed(run, params, state, opt_state, images, labels, steps):
    import time

    t0 = time.perf_counter()
    float(run(params, state, opt_state, images, labels))  # compile+warmup
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(run(params, state, opt_state, images, labels))
    dt = time.perf_counter() - t0
    return dt / steps, compile_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--promote", action="store_true",
                    help="write the winning config to bench_config.json "
                         "(picked up by bench.py on TPU)")
    args = ap.parse_args()

    import jax
    import optax

    from tensorflowonspark_tpu.models import resnet

    dev = jax.devices()[0]
    peak = 197e12  # v5e bf16
    flops_img = 3.0 * resnet.flops_per_image(50, args.image)
    print(f"device: {dev} ({getattr(dev, 'device_kind', '?')})", flush=True)

    opt = optax.sgd(0.1, momentum=0.9)

    @jax.jit
    def init_all(key):
        params, state = resnet.init(jax.random.PRNGKey(0), depth=50,
                                    num_classes=1000)
        return params, state, opt.init(params)

    print("init...", flush=True)
    params, state, opt_state = init_all(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    print("init done", flush=True)

    configs = list(CONFIGS)
    subset = os.environ.get("TFOS_SWEEP")
    if subset:
        want = set(subset.split(","))
        configs = [c for c in configs if c[0] in want]
    # SMOKE: plumbing check (CPU) — tiny shapes AND promote refused.
    # TINY: tiny shapes only — promote logic still runs, so fake-TPU
    # dry-run tests can drive the real promote/merge/refusal branches.
    if os.environ.get("TFOS_SWEEP_SMOKE") == "1" \
            or os.environ.get("TFOS_SWEEP_TINY") == "1":
        configs = [(n, 4, s, r, bf) for n, _, s, r, bf in configs[:2]]

    # TFOS_SWEEP_MODE=fwd|grad decomposes the step (no remat support,
    # no promote: fwd/grad "mfu" is not comparable to the train metric)
    mode = os.environ.get("TFOS_SWEEP_MODE", "train")
    if mode not in ("train", "fwd", "grad"):
        raise SystemExit(f"bad TFOS_SWEEP_MODE {mode!r}")

    rng = np.random.default_rng(0)
    results = []
    by_name = {}
    for name, batch, s2d, remat, bnf in configs:
        try:
            import jax.numpy as jnp

            images = jnp.asarray(
                rng.random((batch, args.image, args.image, 3),
                           dtype=np.float32), jnp.bfloat16)
            labels = jnp.asarray(rng.integers(0, 1000, batch), jnp.int32)
            if mode == "train":
                step_fn = resnet.make_train_step(
                    opt, depth=50, stem_s2d=s2d, remat=remat, bn_fused=bnf)
                sec, compile_s = measure(
                    step_fn, params, state, opt_state, images, labels,
                    args.steps)
            elif remat:
                # decomposed builds ignore remat - timing a non-remat
                # program under a *_remat name would mislabel it (and
                # risk the HBM-pressure compile crash remat avoids)
                print(f"{name:18s} SKIPPED ({mode} mode has no remat)",
                      flush=True)
                continue
            else:
                sec, compile_s = measure_decomposed(
                    mode, opt, {"stem_s2d": s2d, "bn_fused": bnf},
                    params, state, opt_state, images, labels, args.steps)
            ips = batch / sec
            mfu = ips * flops_img / peak
            print(f"{name:18s} {mode}={sec*1e3:7.1f}ms  img/s={ips:7.0f}  "
                  f"mfu={mfu:.4f}  (compile {compile_s:.0f}s)", flush=True)
            results.append((mfu, name))
            by_name[name] = {"batch": batch, "stem_s2d": s2d, "remat": remat,
                             "bn_fused": bnf}
        except Exception as e:  # noqa: BLE001 - keep sweeping
            print(f"{name:18s} FAILED: {str(e)[:160]}", flush=True)
    for mfu, name in sorted(results, reverse=True):
        print(f"  {mfu:.4f}  {name}")
    if args.promote and results:
        import json

        if mode != "train":
            print(f"promote skipped: TFOS_SWEEP_MODE={mode} times a "
                  f"partial step - not the bench metric", flush=True)
            return
        tiny = os.environ.get("TFOS_SWEEP_TINY") == "1" and \
            os.environ.get("TFOS_SWEEP_TINY_PROMOTE_OK") != "1"
        if os.environ.get("TFOS_SWEEP_SMOKE") == "1" or tiny or \
                dev.platform == "cpu":
            # TINY shrinks configs to toy shapes too: a leftover env var
            # during a live claim must not pin the bench to batch 4
            # (dry-run tests set TFOS_SWEEP_TINY_PROMOTE_OK explicitly)
            print("promote skipped: smoke/CPU/tiny runs must not pin the "
                  "TPU bench to toy shapes", flush=True)
            return
        best_mfu, best = max(results)
        path = config_path()
        cfg_all = {}
        prior_mfu, prior_winner = 0, None
        if os.path.exists(path):  # keep other sections (e.g. transformer)
            try:
                with open(path) as f:
                    prior = json.load(f)
                prior_mfu = prior.get("mfu", 0) or 0
                prior_winner = prior.get("winner")
                cfg_all = {k: v for k, v in prior.items()
                           if isinstance(v, dict)}  # nested sections only
            except (OSError, ValueError):
                cfg_all = {}
        if prior_mfu > best_mfu:
            # a subset re-sweep must not demote a better earlier winner
            print(f"promote kept prior {prior_winner} "
                  f"(mfu {prior_mfu:.4f} > {best_mfu:.4f})", flush=True)
            return
        cfg_all.update(by_name[best], image=args.image, winner=best,
                       mfu=round(best_mfu, 4), device=str(dev))
        with open(path, "w") as f:
            json.dump(cfg_all, f, indent=1)
        print(f"promoted {best} (mfu {best_mfu:.4f}) -> {path}", flush=True)


if __name__ == "__main__":
    main()
