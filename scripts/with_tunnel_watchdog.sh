#!/bin/bash
# Run one chip-session step under a tunnel watchdog.
#
# The axon relay has died mid-step twice this round; a step blocked on
# a dead relay otherwise burns its full `timeout` budget (up to 2 h)
# doing nothing — and nothing inside the VM can restart the relay (its
# stdio is wired to the host), so a closed port is terminal.  This
# wrapper kills the step's whole process group (sweeps/bench spawn
# feeder children) within ~1 min of the relay port closing.
#
# Usage: with_tunnel_watchdog.sh <timeout_s> cmd...
# Exit: the command's rc; 124 on timeout; 86 when the relay died
#       (callers should abort the whole session on 86).
set -u
tmo=$1; shift
port=${TFOS_RELAY_PORT:-8082}

# TFOS_WATCHDOG_DISABLE=1: no relay to watch (CPU smoke/dry runs) —
# degrade to a plain bounded run
if [ "${TFOS_WATCHDOG_DISABLE:-0}" = "1" ]; then
  exec timeout "$tmo" "$@"
fi

setsid "$@" &
pid=$!
# the step runs detached in its own session and never sees the
# terminal's SIGINT — forward INT/TERM to the whole group so an
# interrupted session can't orphan a jax-on-axon process holding the
# serialized TPU claim
trap 'kill -9 -- "-$pid" 2>/dev/null; exit 130' INT TERM
deadline=$(( $(date +%s) + tmo ))
closed=0
while kill -0 "$pid" 2>/dev/null; do
  if [ "$(date +%s)" -ge "$deadline" ]; then
    echo "WATCHDOG: step exceeded ${tmo}s; killing process group" >&2
    kill -9 -- "-$pid" 2>/dev/null
    wait "$pid" 2>/dev/null
    exit 124
  fi
  if timeout 2 bash -c "echo > /dev/tcp/127.0.0.1/$port" 2>/dev/null; then
    closed=0
  else
    closed=$((closed + 1))
    if [ "$closed" -ge 4 ]; then
      echo "WATCHDOG: relay port $port closed (4 consecutive probes) -" \
           "tunnel is dead; killing process group" >&2
      kill -9 -- "-$pid" 2>/dev/null
      wait "$pid" 2>/dev/null
      exit 86
    fi
  fi
  sleep 15
done
wait "$pid"
exit $?
