"""One-process transformer perf sweep under a single TPU claim:
batch size x flash block sizes at dim 1024 / 8 layers / seq 2048
(the bench.py flagship config; bf16 logits freed ~2GB HBM, so batch 16
should now fit).

Usage: python scripts/sweep_transformer.py [--steps 8]
"""

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# (name, batch, block_q, block_kv, remat, bwd, ce[, seq]) — module-level
# so dry-run tests can substitute tiny shapes while driving the REAL
# promote paths.  ce: "dense" | "block" (blockwise streamed CE — no
# [B,S,V] logits tensor, buys batch headroom without full remat).
# seq defaults to 2048 (the bench flagship); long-seq configs append an
# explicit seq — rope is position-parameterized so params are shared.
CONFIGS = [
    ("b16_q512_kv512", 16, 512, 512, False, "xla", "dense"),
    ("b16_q512_kv512_pbwd", 16, 512, 512, False, "pallas", "dense"),
    ("b8_q512_kv512", 8, 512, 512, False, "xla", "dense"),
    ("b16_q1024_kv512", 16, 1024, 512, False, "xla", "dense"),
    ("b16_q512_kv1024", 16, 512, 1024, False, "xla", "dense"),
    ("b16_q1024_kv1024", 16, 1024, 1024, False, "xla", "dense"),
    ("b32_q512_kv512", 32, 512, 512, False, "xla", "dense"),
    ("b32_q512_kv512_bce", 32, 512, 512, False, "xla", "block"),
    ("b32_q512_kv512_remat", 32, 512, 512, True, "xla", "dense"),
    ("b32_q512_kv512_remat_pbwd", 32, 512, 512, True, "pallas", "dense"),
    ("b64_q512_kv512_bce", 64, 512, 512, False, "xla", "block"),
    ("b64_q512_kv512_remat", 64, 512, 512, True, "xla", "dense"),
    ("b64_q512_kv512_remat_bce", 64, 512, 512, True, "xla", "block"),
    # r4 follow-ups around the first chip session's winner
    # (b32_q512_kv512_remat_pbwd, 0.4826): pallas bwd at other
    # batch/block points, and pallas bwd + blockwise CE together
    ("b64_q512_kv512_remat_pbwd", 64, 512, 512, True, "pallas", "dense"),
    ("b32_q1024_kv1024_remat_pbwd", 32, 1024, 1024, True, "pallas", "dense"),
    ("b64_q512_kv512_remat_pbwd_bce", 64, 512, 512, True, "pallas", "block"),
    ("b32_q512_kv512_remat_pbwd_bce", 32, 512, 512, True, "pallas", "block"),
    ("b16_q512_kv512_remat_pbwd", 16, 512, 512, True, "pallas", "dense"),
    # selective remat around the r4 winner (b64_q512_kv512_remat_pbwd,
    # 0.4874): "dots" saves matmul outputs and recomputes only the
    # elementwise chain — less recompute than full remat but more HBM
    # residency.  Configs that trip the deterministic HBM-pressure
    # compile crash die in ~6s and the sweep keeps going.
    ("b64_q512_kv512_rdots_pbwd", 64, 512, 512, "dots", "pallas", "dense"),
    ("b96_q512_kv512_rdots_pbwd", 96, 512, 512, "dots", "pallas", "dense"),
    ("b96_q512_kv512_remat_pbwd", 96, 512, 512, True, "pallas", "dense"),
    # r5: seq 4096 — the regime blockwise CE exists for (VERDICT r4 #8:
    # at seq 2048 it merely loses ~3%; at 4096 the dense [B,S,V] logits
    # tensor doubles while blockwise stays O(block)).  Same token count
    # as the b32/s2048 winner; direct dense-vs-bce A/B at each batch.
    ("b16_s4096_remat_pbwd_bce", 16, 512, 512, True, "pallas", "block", 4096),
    ("b16_s4096_remat_pbwd", 16, 512, 512, True, "pallas", "dense", 4096),
    ("b32_s4096_remat_pbwd_bce", 32, 512, 512, True, "pallas", "block", 4096),
    # follow-up if the 4096 trio wins: bigger flash blocks amortize the
    # per-block epilogue over a longer diagonal
    ("b16_s4096_q1024_kv1024_remat_pbwd_bce",
     16, 1024, 1024, True, "pallas", "block", 4096),
]


def config_path():
    """bench_config.json location — resolved by bench.bench_config_path
    (the single source of truth; TFOS_BENCH_CONFIG overrides)."""
    import bench

    return bench.bench_config_path()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--promote", action="store_true",
                    help="write the winner into bench_config.json's "
                         '"transformer" section (picked up by bench.py)')
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from tensorflowonspark_tpu import ops
    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.utils import metrics as M

    smoke = os.environ.get("TFOS_SWEEP_SMOKE") == "1"
    # TINY shrinks shapes like smoke but leaves the promote logic live
    # (fake-TPU dry-run tests drive the real promote/merge branches)
    tiny = smoke or os.environ.get("TFOS_SWEEP_TINY") == "1"
    cfg = transformer.Config(
        vocab_size=512 if tiny else 16384,
        dim=128 if tiny else 1024,
        n_layers=2 if tiny else 8,
        n_heads=4 if tiny else 8,
        max_seq=256 if tiny else 2048,
        dtype="float32" if tiny else "bfloat16",
        attn_impl="flash",
    )
    peak = 197e12
    flops_tok = M.transformer_flops_per_token(cfg)
    opt = optax.adam(1e-3)

    @jax.jit
    def init_all(key):
        params = transformer.init(key, cfg)
        return params, opt.init(params)

    print("init...", flush=True)
    params, opt_state = init_all(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    print("init done", flush=True)

    # pre-flight: the compiled (Mosaic-lowered) pallas forward has never
    # run before the first chip session — if it miscompiles, every config
    # here uses it and the sweep would produce NOTHING.  Probe once; on
    # failure sweep with the XLA reference attention instead (slower but
    # a number, recorded as attn="reference" for the bench to honor).
    attn_base, attn_name = ops.flash_attention, "flash"
    # probe at the BLOCK SIZES the real configs use, on random input,
    # and check numerics against the XLA reference — a kernel that
    # miscompiles only at production shapes, or compiles but returns
    # garbage, must also trip the fallback.  Inputs and the reference
    # output are computed OUTSIDE the guarded region: if plain XLA fails
    # here the backend is broken and the sweep should fail loudly, not
    # quietly demote to the slow path.
    pseq = min(1024, cfg.max_seq)
    pkeys = jax.random.split(jax.random.PRNGKey(7), 3)
    pq, pk_, pv = (jax.random.normal(
        kk, (1, pseq, cfg.n_heads, cfg.head_dim), cfg.compute_dtype)
        for kk in pkeys)
    ref_out = ops.mha_reference(pq, pk_, pv, causal=True)
    flash_probe = jax.jit(functools.partial(
        ops.flash_attention, causal=True, block_q=512, block_kv=512))
    for attempt in (1, 2):
        try:
            got = flash_probe(pq, pk_, pv)
            err = float(jnp.max(jnp.abs(
                got.astype(jnp.float32) - ref_out.astype(jnp.float32))))
            if not err < 5e-2:  # bf16-scale tolerance; also catches NaN
                raise RuntimeError(f"probe numerics off: max err {err}")
            break
        except Exception as e:  # noqa: BLE001 - first-run kernel failure
            # retry ONLY transient pool errors, after letting them clear
            # (observed to clear in minutes; mirrors bench's init retry).
            # Deterministic failures — Mosaic miscompiles, bad numerics —
            # go straight to the fallback: a doomed re-compile would burn
            # many minutes of the one serialized TPU claim.
            if attempt == 1 and "UNAVAILABLE" in str(e):
                print(f"pallas probe hit a transient pool error "
                      f"({str(e)[:120]}); retrying in 60s", flush=True)
                time.sleep(60)
                continue
            print(f"pallas flash forward FAILED on this backend: "
                  f"{str(e)[:200]}\nsweeping with the XLA reference "
                  f"attention instead", flush=True)
            attn_base, attn_name = ops.mha_reference, "reference"
            break

    # normalize to 8-tuples (seq defaults to the flagship 2048)
    configs = [(*c, cfg.max_seq) if len(c) == 7 else tuple(c)
               for c in CONFIGS]
    subset = os.environ.get("TFOS_SWEEP")
    if subset:
        want = set(subset.split(","))
        configs = [c for c in configs if c[0] in want]
    if tiny:  # plumbing check (CPU): tiny batch, blocks fitting
        # max_seq, always including one remat, one pallas-bwd, one
        # blockwise-CE, and one long-seq config
        picked = (configs[:2] + [c for c in configs[2:] if c[4]][:1]
                  + [c for c in configs[2:] if c[5] == "pallas"][:1]
                  + [c for c in configs[2:] if c[6] == "block"][:1]
                  + [c for c in configs[2:] if c[7] != cfg.max_seq][:1])
        configs = [(n, 1, min(bq, 128), min(bkv, 128), r, bw, ce,
                    cfg.max_seq * (2 if s != cfg.max_seq else 1))
                   for n, _, bq, bkv, r, bw, ce, s in picked]

    import dataclasses

    rng = np.random.default_rng(0)
    results = []
    by_name = {}
    seen_ref = set()  # reference attn ignores blocks: dedupe configs
    for name, batch, bq, bkv, remat, bwd, ce, seq in configs:
        ccfg = (cfg if seq == cfg.max_seq
                else dataclasses.replace(cfg, max_seq=seq))
        cflops_tok = (flops_tok if seq == cfg.max_seq
                      else M.transformer_flops_per_token(ccfg))
        if attn_name == "reference":
            if bwd == "pallas":
                print(f"{name:18s} SKIPPED (pallas unavailable)",
                      flush=True)
                continue
            key = (batch, remat, ce, seq)
            if key in seen_ref:  # blocks don't matter without pallas —
                # don't burn multi-minute tunnel compiles on duplicates
                print(f"{name:18s} SKIPPED (duplicate under reference "
                      f"attn)", flush=True)
                continue
            seen_ref.add(key)
        try:
            tokens = jnp.asarray(
                rng.integers(0, ccfg.vocab_size, (batch, ccfg.max_seq)),
                jnp.int32)
            if attn_name == "flash":
                attn = functools.partial(
                    attn_base, causal=True, block_q=bq,
                    block_kv=bkv, bwd_impl=bwd)
            else:
                attn = functools.partial(attn_base, causal=True)

            @jax.jit
            def run(params, opt_state, tokens):
                def body(carry, _):
                    p, o = carry
                    loss, grads = jax.value_and_grad(transformer.loss_fn)(
                        p, tokens, ccfg, attn_fn=attn, remat=remat,
                        ce_impl=("blockwise" if ce == "block" else "dense"),
                        ce_block=min(2048, ccfg.vocab_size))
                    updates, o = opt.update(grads, o)
                    return (optax.apply_updates(p, updates), o), loss
                (_, _), losses = lax.scan(
                    body, (params, opt_state), None, length=args.steps)
                return losses[-1]

            t0 = time.perf_counter()
            float(run(params, opt_state, tokens))
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(run(params, opt_state, tokens))
            dt = time.perf_counter() - t0
            tps = batch * ccfg.max_seq * args.steps / dt
            mfu = tps * cflops_tok / peak
            print(f"{name:22s} tok/s={tps:9.0f}  mfu={mfu:.4f}  "
                  f"(compile {compile_s:.0f}s)", flush=True)
            results.append((mfu, name))
            by_name[name] = {"batch": batch, "block_q": bq,
                             "block_kv": bkv, "remat": remat, "bwd": bwd,
                             "ce": ce, "attn": attn_name, "seq": seq}
        except Exception as e:  # noqa: BLE001 - keep sweeping
            print(f"{name:18s} FAILED: {str(e)[:160]}", flush=True)
    for mfu, name in sorted(results, reverse=True):
        print(f"  {mfu:.4f}  {name}")
    if args.promote and results:
        import json

        tiny_guard = tiny and \
            os.environ.get("TFOS_SWEEP_TINY_PROMOTE_OK") != "1"
        if smoke or tiny_guard or jax.devices()[0].platform == "cpu":
            # TINY shrinks shapes too (see sweep_resnet.py): only the
            # dry-run tests may promote tiny results, via the explicit
            # TFOS_SWEEP_TINY_PROMOTE_OK acknowledgement
            print("promote skipped: smoke/CPU/tiny runs must not pin the "
                  "TPU bench to toy shapes", flush=True)
            return
        best_mfu, best = max(results)
        path = config_path()
        cfg_all = {}
        if os.path.exists(path):  # keep the resnet section
            try:
                with open(path) as f:
                    cfg_all = json.load(f)
            except (OSError, ValueError):
                cfg_all = {}
        prior = cfg_all.get("transformer", {})
        if isinstance(prior, dict) and prior.get("mfu", 0) > best_mfu:
            # a subset re-sweep must not demote a better earlier winner
            print(f"promote kept prior {prior.get('winner')} "
                  f"(mfu {prior['mfu']:.4f} > {best_mfu:.4f})", flush=True)
            return
        cfg_all["transformer"] = dict(
            by_name[best], winner=best, mfu=round(best_mfu, 4))
        with open(path, "w") as f:
            json.dump(cfg_all, f, indent=1)
        print(f"promoted {best} (mfu {best_mfu:.4f}) -> {path}", flush=True)


if __name__ == "__main__":
    main()
