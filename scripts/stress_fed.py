"""Fed-path CONSUMER stress bench (VERDICT r3 next #6c): real feeder
process -> shm ring -> DataFeed, drained with NO device compute, so the
number is the consumer-side ceiling (records/s) that bounds fed training
throughput on a chip.

Two modes, A/B-able in one run:
  rows     — row-list chunks + next_batch + np.stack collate (the
             round-2/3 hot path; PERF.md measured its np.stack wall at
             ~12k img/s single-threaded at 224px)
  columnar — ColumnChunk wire format (flattened uint8 image columns) +
             next_batch_columns dense pull (round-4 fast path)

Usage: python scripts/stress_fed.py [--batch 256] [--image 224]
           [--steps 24] [--mode both|rows|columnar|pipeline]
Prints one JSON line per mode:
  {"mode", "records_per_sec", "batches", "batch", "image"}

``--mode pipeline`` runs the composed-pipeline A/B on the 784-float
workload (ISSUE 5 acceptance): a per-record fed feeder (row append +
columnar encode, the node.train closure idiom) vs the data/ pipeline
graph (vectorized map -> batch -> prefetch -> ColumnChunk) pushing the
SAME ring drained by the SAME DataFeed consumer; prints
``pipeline_vs_fed`` speedup (>= 1.0 means the composed pipeline
matches/beats the fed path)."""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflowonspark_tpu.utils import telemetry  # noqa: E402


def _f784_feeder_main(ring_name, mgr_addr, authkey_hex, total, width):
    """Fed-baseline feeder for the 784-float workload: the per-record
    row-append loop + columnar chunk encoder, exactly the node.train
    feeder idiom (node.py) — the cost model the composed pipeline has to
    match or beat."""
    import numpy as np

    import bench
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu import node as tfnode
    from tensorflowonspark_tpu.recordio import shm as shmq

    if telemetry.enabled():
        telemetry.configure(node_id=f"feeder-{os.getpid()}", role="feeder")
    encode = tfnode._make_chunk_encoder()
    mgr = tfmanager.connect(tuple(mgr_addr), bytes.fromhex(authkey_hex))
    ring = shmq.ShmQueue(ring_name, create=False, producer=True)
    rng = np.random.default_rng(0)
    pool = 2 * bench.FED_CHUNK
    vecs = [rng.random(width, dtype=np.float32) for _ in range(pool)]
    sent = 0
    chunk = []
    with telemetry.span("feeder/push", records=total, columnar=True):
        while sent < total:
            chunk.append((vecs[sent % pool] * (1.0 / 255.0),
                          sent % 1000))
            sent += 1
            if len(chunk) >= bench.FED_CHUNK:
                ring.put(encode(chunk))
                chunk = []
        if chunk:
            ring.put(encode(chunk))
        ring.put(None)  # end-of-feed marker
    ring.close()
    mgr.set("feeder_done", 1)
    telemetry.flush()


def _pipeline_feeder_main(ring_name, mgr_addr, authkey_hex, total, width):
    """Composed-pipeline feeder: the same 784-float workload through the
    data/ graph — vectorized map, batch, prefetch — emitting ColumnChunk
    blocks straight onto the ring (no per-record python)."""
    import numpy as np

    import bench
    from tensorflowonspark_tpu import data
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu.recordio import shm as shmq

    if telemetry.enabled():
        telemetry.configure(node_id=f"feeder-{os.getpid()}", role="feeder")
    mgr = tfmanager.connect(tuple(mgr_addr), bytes.fromhex(authkey_hex))
    ring = shmq.ShmQueue(ring_name, create=False, producer=True)
    rng = np.random.default_rng(0)
    x = rng.random((total, width), dtype=np.float32)
    y = (np.arange(total, dtype=np.int64) % 1000)
    pipe = (data.from_arrays({"image": x, "label": y},
                             block_size=bench.FED_CHUNK)
            .map(lambda b: {"image": b["image"] * (1.0 / 255.0),
                            "label": b["label"]})
            .batch(bench.FED_CHUNK)
            .prefetch(4))
    with telemetry.span("feeder/push", records=total, pipeline=True):
        for chunk in pipe.chunks():
            ring.put(chunk)
        ring.put(None)  # end-of-feed marker
    ring.close()
    mgr.set("feeder_done", 1)
    telemetry.flush()


def run_f784(mode, batch, width, steps):
    """One 784-float lane: mode 'fed784' (row feeder) or 'pipeline784'
    (composed graph), drained by the identical DataFeed consumer."""
    import numpy as np

    import bench
    from tensorflowonspark_tpu.feed import DataFeed

    target = (_pipeline_feeder_main if mode == "pipeline784"
              else _f784_feeder_main)
    fed = bench._fed_setup(batch, 0, steps, tag=f"-{mode}", target=target,
                           extra=(width,), rec_bytes=width * 4)
    if fed is None:
        return {"mode": mode, "error": "shm unavailable"}
    feed = DataFeed(fed["mgr"], train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    n_batches = 0
    n_records = 0
    t0 = None
    dt = 0.0
    try:
        while not feed.should_stop():
            cols = feed.next_batch_columns(batch)
            vecs = cols["image"]
            labels = np.asarray(cols["label"], np.int32)
            n = len(labels)
            if n == 0:
                continue
            assert vecs.shape[1] == width, vecs.shape
            if t0 is None:  # skip the first batch (warmup)
                t0 = time.perf_counter()
            else:
                n_batches += 1
                n_records += n
        dt = time.perf_counter() - t0 if t0 is not None else 0.0
    finally:
        fed["proc"].join(timeout=10)
        if fed["proc"].is_alive():
            fed["proc"].kill()
        fed["mgr"].set("state", "stopped")
        fed["ring"].close()
    rps = n_records / dt if dt > 0 else 0.0
    return {"mode": mode, "records_per_sec": round(rps, 1),
            "batches": n_batches, "batch": batch, "width": width}


def run_mode(mode, batch, image, steps):
    import numpy as np

    import bench
    from tensorflowonspark_tpu.feed import DataFeed

    fed = bench._fed_setup(batch, image, steps,
                           columnar=(mode == "columnar"), tag=f"-{mode}")
    if fed is None:
        return {"mode": mode, "error": "shm unavailable"}
    feed = DataFeed(fed["mgr"], train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    n_batches = 0
    n_records = 0
    t0 = None
    dt = 0.0
    try:
        while not feed.should_stop():
            if mode == "columnar":
                cols = feed.next_batch_columns(batch)
                imgs = cols["image"]
                labels = np.asarray(cols["label"], np.int32)
            else:
                cols = feed.next_batch(batch)
                if not cols["image"]:
                    continue
                imgs = np.stack(cols["image"])
                labels = np.asarray(cols["label"], np.int32)
            n = len(labels)
            if n == 0:
                continue
            assert imgs.shape[1:] == (image, image, 3), imgs.shape
            if t0 is None:  # skip the first batch (warmup/compile-free)
                t0 = time.perf_counter()
            else:
                n_batches += 1
                n_records += n
        # stop the clock BEFORE teardown: proc.join/ring.close cost
        # 100ms+ and would deflate short runs' records_per_sec
        dt = time.perf_counter() - t0 if t0 is not None else 0.0
    finally:
        fed["proc"].join(timeout=10)
        if fed["proc"].is_alive():
            fed["proc"].kill()
        fed["mgr"].set("state", "stopped")
        fed["ring"].close()
    rps = n_records / dt if dt > 0 else 0.0
    return {"mode": mode, "records_per_sec": round(rps, 1),
            "batches": n_batches, "batch": batch, "image": image}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--mode", choices=("both", "rows", "columnar",
                                       "pipeline"),
                    default="both")
    ap.add_argument("--width", type=int, default=784,
                    help="record width for the --mode pipeline A/B lane")
    args = ap.parse_args()
    if os.environ.get(telemetry.DIR_ENV):
        # opt-in spans, same schema/dir layout as bench.py and the
        # cluster nodes (feed/wait comes from DataFeed when enabled)
        telemetry.configure(node_id="stress-fed", role="stress")
    if args.mode == "pipeline":
        results = []
        for m in ("fed784", "pipeline784"):
            with telemetry.span(f"stress_fed/{m}", batch=args.batch,
                                width=args.width, steps=args.steps) as sp:
                r = run_f784(m, args.batch, args.width, args.steps)
                if "records_per_sec" in r:
                    sp.add(records_per_sec=r["records_per_sec"])
            print(json.dumps(r), flush=True)
            results.append(r)
        if all("records_per_sec" in r for r in results):
            a, b = (results[0]["records_per_sec"],
                    results[1]["records_per_sec"])
            if a:
                print(json.dumps({"pipeline_vs_fed": round(b / a, 2)}),
                      flush=True)
        telemetry.flush()
        return
    modes = (["rows", "columnar"] if args.mode == "both" else [args.mode])
    results = []
    for m in modes:
        with telemetry.span(f"stress_fed/{m}", batch=args.batch,
                            image=args.image, steps=args.steps) as sp:
            r = run_mode(m, args.batch, args.image, args.steps)
            if "records_per_sec" in r:
                sp.add(records_per_sec=r["records_per_sec"])
        print(json.dumps(r), flush=True)
        results.append(r)
    if len(results) == 2 and all("records_per_sec" in r for r in results):
        a, b = results[0]["records_per_sec"], results[1]["records_per_sec"]
        if a:
            print(json.dumps({"columnar_speedup": round(b / a, 2)}),
                  flush=True)
    telemetry.flush()


if __name__ == "__main__":
    main()
