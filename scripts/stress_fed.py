"""Fed-path CONSUMER stress bench (VERDICT r3 next #6c): real feeder
process -> shm ring -> DataFeed, drained with NO device compute, so the
number is the consumer-side ceiling (records/s) that bounds fed training
throughput on a chip.

Two modes, A/B-able in one run:
  rows     — row-list chunks + next_batch + np.stack collate (the
             round-2/3 hot path; PERF.md measured its np.stack wall at
             ~12k img/s single-threaded at 224px)
  columnar — ColumnChunk wire format (flattened uint8 image columns) +
             next_batch_columns dense pull (round-4 fast path)

Usage: python scripts/stress_fed.py [--batch 256] [--image 224]
           [--steps 24] [--mode both|rows|columnar]
Prints one JSON line per mode:
  {"mode", "records_per_sec", "batches", "batch", "image"}
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflowonspark_tpu.utils import telemetry  # noqa: E402


def run_mode(mode, batch, image, steps):
    import numpy as np

    import bench
    from tensorflowonspark_tpu.feed import DataFeed

    fed = bench._fed_setup(batch, image, steps,
                           columnar=(mode == "columnar"), tag=f"-{mode}")
    if fed is None:
        return {"mode": mode, "error": "shm unavailable"}
    feed = DataFeed(fed["mgr"], train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    n_batches = 0
    n_records = 0
    t0 = None
    dt = 0.0
    try:
        while not feed.should_stop():
            if mode == "columnar":
                cols = feed.next_batch_columns(batch)
                imgs = cols["image"]
                labels = np.asarray(cols["label"], np.int32)
            else:
                cols = feed.next_batch(batch)
                if not cols["image"]:
                    continue
                imgs = np.stack(cols["image"])
                labels = np.asarray(cols["label"], np.int32)
            n = len(labels)
            if n == 0:
                continue
            assert imgs.shape[1:] == (image, image, 3), imgs.shape
            if t0 is None:  # skip the first batch (warmup/compile-free)
                t0 = time.perf_counter()
            else:
                n_batches += 1
                n_records += n
        # stop the clock BEFORE teardown: proc.join/ring.close cost
        # 100ms+ and would deflate short runs' records_per_sec
        dt = time.perf_counter() - t0 if t0 is not None else 0.0
    finally:
        fed["proc"].join(timeout=10)
        if fed["proc"].is_alive():
            fed["proc"].kill()
        fed["mgr"].set("state", "stopped")
        fed["ring"].close()
    rps = n_records / dt if dt > 0 else 0.0
    return {"mode": mode, "records_per_sec": round(rps, 1),
            "batches": n_batches, "batch": batch, "image": image}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--mode", choices=("both", "rows", "columnar"),
                    default="both")
    args = ap.parse_args()
    if os.environ.get(telemetry.DIR_ENV):
        # opt-in spans, same schema/dir layout as bench.py and the
        # cluster nodes (feed/wait comes from DataFeed when enabled)
        telemetry.configure(node_id="stress-fed", role="stress")
    modes = (["rows", "columnar"] if args.mode == "both" else [args.mode])
    results = []
    for m in modes:
        with telemetry.span(f"stress_fed/{m}", batch=args.batch,
                            image=args.image, steps=args.steps) as sp:
            r = run_mode(m, args.batch, args.image, args.steps)
            if "records_per_sec" in r:
                sp.add(records_per_sec=r["records_per_sec"])
        print(json.dumps(r), flush=True)
        results.append(r)
    if len(results) == 2 and all("records_per_sec" in r for r in results):
        a, b = results[0]["records_per_sec"], results[1]["records_per_sec"]
        if a:
            print(json.dumps({"columnar_speedup": round(b / a, 2)}),
                  flush=True)
    telemetry.flush()


if __name__ == "__main__":
    main()
