"""Fed-path CONSUMER stress bench (VERDICT r3 next #6c): real feeder
process -> shm ring -> DataFeed, drained with NO device compute, so the
number is the consumer-side ceiling (records/s) that bounds fed training
throughput on a chip.

Two modes, A/B-able in one run:
  rows     — row-list chunks + next_batch + np.stack collate (the
             round-2/3 hot path; PERF.md measured its np.stack wall at
             ~12k img/s single-threaded at 224px)
  columnar — ColumnChunk wire format (flattened uint8 image columns) +
             next_batch_columns dense pull (round-4 fast path)

Usage: python scripts/stress_fed.py [--batch 256] [--image 224]
           [--steps 24] [--mode both|rows|columnar|pipeline|service-dynamic]
Prints one JSON line per mode:
  {"mode", "records_per_sec", "batches", "batch", "image"}

``--mode service-dynamic`` runs the straggler A/B of the data service
(ISSUE 19 acceptance): T consumer processes with one seeded
``--slow-factor``x slower (faults.py ``feed.get:delay``), an epoch
served three ways — dynamic dispatch homogeneous, dynamic with the
straggler, static ``shard(rank,T)`` with the straggler — printing
``straggler_ratio`` (dynamic-straggler / homogeneous, gate <= 1.5) and
``straggler_speedup`` (static-straggler / dynamic-straggler).

``--mode pipeline`` runs the composed-pipeline A/B on the 784-float
workload (ISSUE 5 acceptance): a per-record fed feeder (row append +
columnar encode, the node.train closure idiom) vs the data/ pipeline
graph (vectorized map -> batch -> prefetch -> ColumnChunk) pushing the
SAME ring drained by the SAME DataFeed consumer; prints
``pipeline_vs_fed`` speedup (>= 1.0 means the composed pipeline
matches/beats the fed path)."""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflowonspark_tpu.utils import telemetry  # noqa: E402


def _f784_feeder_main(ring_name, mgr_addr, authkey_hex, total, width):
    """Fed-baseline feeder for the 784-float workload: the per-record
    row-append loop + columnar chunk encoder, exactly the node.train
    feeder idiom (node.py) — the cost model the composed pipeline has to
    match or beat."""
    import numpy as np

    import bench
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu import node as tfnode
    from tensorflowonspark_tpu.recordio import shm as shmq

    if telemetry.enabled():
        telemetry.configure(node_id=f"feeder-{os.getpid()}", role="feeder")
    encode = tfnode._make_chunk_encoder()
    mgr = tfmanager.connect(tuple(mgr_addr), bytes.fromhex(authkey_hex))
    ring = shmq.ShmQueue(ring_name, create=False, producer=True)
    rng = np.random.default_rng(0)
    pool = 2 * bench.FED_CHUNK
    vecs = [rng.random(width, dtype=np.float32) for _ in range(pool)]
    sent = 0
    chunk = []
    with telemetry.span("feeder/push", records=total, columnar=True):
        while sent < total:
            chunk.append((vecs[sent % pool] * (1.0 / 255.0),
                          sent % 1000))
            sent += 1
            if len(chunk) >= bench.FED_CHUNK:
                ring.put(encode(chunk))
                chunk = []
        if chunk:
            ring.put(encode(chunk))
        ring.put(None)  # end-of-feed marker
    ring.close()
    mgr.set("feeder_done", 1)
    telemetry.flush()


def _pipeline_feeder_main(ring_name, mgr_addr, authkey_hex, total, width):
    """Composed-pipeline feeder: the same 784-float workload through the
    data/ graph — vectorized map, batch, prefetch — emitting ColumnChunk
    blocks straight onto the ring (no per-record python)."""
    import numpy as np

    import bench
    from tensorflowonspark_tpu import data
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu.recordio import shm as shmq

    if telemetry.enabled():
        telemetry.configure(node_id=f"feeder-{os.getpid()}", role="feeder")
    mgr = tfmanager.connect(tuple(mgr_addr), bytes.fromhex(authkey_hex))
    ring = shmq.ShmQueue(ring_name, create=False, producer=True)
    rng = np.random.default_rng(0)
    x = rng.random((total, width), dtype=np.float32)
    y = (np.arange(total, dtype=np.int64) % 1000)
    pipe = (data.from_arrays({"image": x, "label": y},
                             block_size=bench.FED_CHUNK)
            .map(lambda b: {"image": b["image"] * (1.0 / 255.0),
                            "label": b["label"]})
            .batch(bench.FED_CHUNK)
            .prefetch(4))
    with telemetry.span("feeder/push", records=total, pipeline=True):
        for chunk in pipe.chunks():
            ring.put(chunk)
        ring.put(None)  # end-of-feed marker
    ring.close()
    mgr.set("feeder_done", 1)
    telemetry.flush()


def run_f784(mode, batch, width, steps):
    """One 784-float lane: mode 'fed784' (row feeder) or 'pipeline784'
    (composed graph), drained by the identical DataFeed consumer."""
    import numpy as np

    import bench
    from tensorflowonspark_tpu.feed import DataFeed

    target = (_pipeline_feeder_main if mode == "pipeline784"
              else _f784_feeder_main)
    fed = bench._fed_setup(batch, 0, steps, tag=f"-{mode}", target=target,
                           extra=(width,), rec_bytes=width * 4)
    if fed is None:
        return {"mode": mode, "error": "shm unavailable"}
    feed = DataFeed(fed["mgr"], train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    n_batches = 0
    n_records = 0
    t0 = None
    dt = 0.0
    try:
        while not feed.should_stop():
            cols = feed.next_batch_columns(batch)
            vecs = cols["image"]
            labels = np.asarray(cols["label"], np.int32)
            n = len(labels)
            if n == 0:
                continue
            assert vecs.shape[1] == width, vecs.shape
            if t0 is None:  # skip the first batch (warmup)
                t0 = time.perf_counter()
            else:
                n_batches += 1
                n_records += n
        dt = time.perf_counter() - t0 if t0 is not None else 0.0
    finally:
        fed["proc"].join(timeout=10)
        if fed["proc"].is_alive():
            fed["proc"].kill()
        fed["mgr"].set("state", "stopped")
        fed["ring"].close()
    rps = n_records / dt if dt > 0 else 0.0
    return {"mode": mode, "records_per_sec": round(rps, 1),
            "batches": n_batches, "batch": batch, "width": width}


def _service_consumer_main(mgr_addr, authkey_hex, batch, plan, done_key):
    """One trainer-side consumer for the service A/B: drains its feed
    queue through DataFeed with a seeded per-chunk cost (the faults.py
    delay machinery), so consumption — not serving — is the bottleneck
    and the dispatch policy is what the wall-clock measures."""
    import os as _os

    if plan:
        _os.environ["TFOS_FAULT_PLAN"] = plan
        _os.environ.pop("TFOS_FAULT_EXECUTOR", None)
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu.feed import DataFeed

    mgr = tfmanager.connect(tuple(mgr_addr), bytes.fromhex(authkey_hex))
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"x": "x", "y": "y"})
    mgr.set("consumer_ready", 1)  # keep process spawn out of the clock
    n = 0
    while not feed.should_stop():
        n += len(feed.next_batch_columns(batch)["y"])
    mgr.set(done_key, n)


class _InlineCtx:
    """Actor-context stand-in to tick SplitProvider in this process."""

    def __init__(self, mgr):
        self.mgr = mgr
        self._kv = {}

    def kv_get(self, key):
        return self._kv.get(key)

    def kv_set(self, key, value):
        self._kv[key] = value


def _run_service_lane(dispatch, trainers, slow_rank, n_blocks, block,
                      delay, slow_factor, split_blocks):
    """One measured epoch through the data service: T consumer processes
    (one optionally ``slow_factor``x slower), serving from this process
    under the given dispatch policy.  Returns wall-clock seconds from
    serve start to the last consumer's exit."""
    import multiprocessing as mp
    import secrets
    import threading

    import numpy as np

    from tensorflowonspark_tpu import data, rendezvous
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu.data import service as dsvc
    from tensorflowonspark_tpu.data import splits as dsplits

    n = n_blocks * block
    arrays = {
        "x": np.zeros((n, 16), dtype=np.float32),
        "y": np.arange(n, dtype=np.int64),
    }
    pipe = data.from_arrays(arrays, block_size=block)
    keys = [secrets.token_bytes(16) for _ in range(trainers)]
    mgrs = [tfmanager.start(k, ["input", "output", "error"]) for k in keys]
    server = rendezvous.Server(1)
    addr = server.start()
    cluster_info = [
        {"executor_id": i, "host": "localhost", "job_name": "worker",
         "addr": list(m.address), "authkey": k.hex()}
        for i, (m, k) in enumerate(zip(mgrs, keys))
    ]
    ctx_mp = mp.get_context("spawn")
    procs = []
    t_wall = None
    try:
        for rank, (m, k) in enumerate(zip(mgrs, keys)):
            d = delay * (slow_factor if rank == slow_rank else 1.0)
            plan = f"feed.get:delay({d})@*"
            p = ctx_mp.Process(
                target=_service_consumer_main,
                args=(tuple(m.address), k.hex(), block, plan, "consumed"),
                daemon=True)
            p.start()
            procs.append(p)
        deadline = time.time() + 60
        while not all(m.get("consumer_ready") for m in mgrs):
            if time.time() > deadline:
                raise RuntimeError("consumers failed to come up")
            time.sleep(0.05)
        t0 = time.perf_counter()
        if dispatch == "dynamic":
            bkey = secrets.token_bytes(16)
            bmgr = tfmanager.start(bkey, [])
            board = dsplits.SplitBoard(bmgr, "input")
            board.set_plan([0])
            ictx = _InlineCtx(bmgr)
            provider = dsplits.SplitProvider(
                "input", server_addr=addr, num_epochs=1,
                window=4 * trainers)
            provider.on_start(ictx)
            meta = {"server_addr": addr,
                    dsvc.SPLIT_BOARD_META: {
                        "address": tuple(bmgr.address), "authkey": bkey}}
            stop = threading.Event()

            def _tick():
                while not stop.is_set() and not board.complete():
                    provider.on_tick(ictx)
                    time.sleep(0.02)

            ticker = threading.Thread(target=_tick, daemon=True)
            ticker.start()
            try:
                dsvc.DynamicDataService(
                    pipe, cluster_info, meta, worker_index=0,
                    split_blocks=split_blocks, feed_timeout=120,
                    use_cache=False).run()
            finally:
                stop.set()
                ticker.join(timeout=5)
                bmgr.shutdown()
        else:
            dsvc.DataService(
                pipe, cluster_info, {"server_addr": addr},
                num_workers=1, worker_index=0,
                feed_timeout=120).run()
        for m in mgrs:
            m.get_queue("input").put(None)  # end-of-feed
        for p in procs:
            p.join(timeout=120)
        t_wall = time.perf_counter() - t0
        consumed = sum(m.get("consumed") or 0 for m in mgrs)
        assert consumed == n, (
            f"{dispatch}: consumed {consumed} of {n} records")
    finally:
        for p in procs:
            if p.is_alive():
                p.kill()
        server.stop()
        for m in mgrs:
            m.shutdown()
    return t_wall


def run_service_dynamic(trainers=4, slow_factor=4.0, n_blocks=160,
                        block=64, delay=0.025, split_blocks=4,
                        queue_cap=2):
    """The straggler A/B (ISSUE 19 acceptance): one consumer
    ``slow_factor``x slower than its siblings.  Static ``shard(rank,T)``
    must stretch the epoch toward ``slow_factor``x; FCFS split dispatch
    keeps it near the homogeneous baseline because the slow trainer
    simply claims fewer splits (gate: ratio <= 1.5).

    A small per-trainer backlog cap (TFOS_DATA_QUEUE_CAP) is what turns
    queue depth into a drain-rate signal — a deep queue would equalize
    LENGTHS, not rates, and hand the slow trainer a fat tail."""
    prev_cap = os.environ.get("TFOS_DATA_QUEUE_CAP")
    os.environ["TFOS_DATA_QUEUE_CAP"] = str(queue_cap)
    try:
        homog = _run_service_lane("dynamic", trainers, slow_rank=-1,
                                  n_blocks=n_blocks, block=block,
                                  delay=delay, slow_factor=slow_factor,
                                  split_blocks=split_blocks)
        dyn = _run_service_lane("dynamic", trainers, slow_rank=0,
                                n_blocks=n_blocks, block=block,
                                delay=delay, slow_factor=slow_factor,
                                split_blocks=split_blocks)
        static = _run_service_lane("static", trainers, slow_rank=0,
                                   n_blocks=n_blocks, block=block,
                                   delay=delay, slow_factor=slow_factor,
                                   split_blocks=split_blocks)
    finally:
        if prev_cap is None:
            os.environ.pop("TFOS_DATA_QUEUE_CAP", None)
        else:
            os.environ["TFOS_DATA_QUEUE_CAP"] = prev_cap
    return {
        "mode": "service-dynamic",
        "trainers": trainers,
        "slow_factor": slow_factor,
        "records": n_blocks * block,
        "homogeneous_s": round(homog, 3),
        "dynamic_straggler_s": round(dyn, 3),
        "static_straggler_s": round(static, 3),
        "straggler_ratio": round(dyn / homog, 2) if homog else 0.0,
        "straggler_speedup": round(static / dyn, 2) if dyn else 0.0,
    }


def run_mode(mode, batch, image, steps):
    import numpy as np

    import bench
    from tensorflowonspark_tpu.feed import DataFeed

    fed = bench._fed_setup(batch, image, steps,
                           columnar=(mode == "columnar"), tag=f"-{mode}")
    if fed is None:
        return {"mode": mode, "error": "shm unavailable"}
    feed = DataFeed(fed["mgr"], train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    n_batches = 0
    n_records = 0
    t0 = None
    dt = 0.0
    try:
        while not feed.should_stop():
            if mode == "columnar":
                cols = feed.next_batch_columns(batch)
                imgs = cols["image"]
                labels = np.asarray(cols["label"], np.int32)
            else:
                cols = feed.next_batch(batch)
                if not cols["image"]:
                    continue
                imgs = np.stack(cols["image"])
                labels = np.asarray(cols["label"], np.int32)
            n = len(labels)
            if n == 0:
                continue
            assert imgs.shape[1:] == (image, image, 3), imgs.shape
            if t0 is None:  # skip the first batch (warmup/compile-free)
                t0 = time.perf_counter()
            else:
                n_batches += 1
                n_records += n
        # stop the clock BEFORE teardown: proc.join/ring.close cost
        # 100ms+ and would deflate short runs' records_per_sec
        dt = time.perf_counter() - t0 if t0 is not None else 0.0
    finally:
        fed["proc"].join(timeout=10)
        if fed["proc"].is_alive():
            fed["proc"].kill()
        fed["mgr"].set("state", "stopped")
        fed["ring"].close()
    rps = n_records / dt if dt > 0 else 0.0
    return {"mode": mode, "records_per_sec": round(rps, 1),
            "batches": n_batches, "batch": batch, "image": image}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--mode", choices=("both", "rows", "columnar",
                                       "pipeline", "service-dynamic"),
                    default="both")
    ap.add_argument("--width", type=int, default=784,
                    help="record width for the --mode pipeline A/B lane")
    ap.add_argument("--trainers", type=int, default=4,
                    help="consumer count for --mode service-dynamic")
    ap.add_argument("--slow-factor", type=float, default=4.0,
                    help="straggler slowdown for --mode service-dynamic")
    args = ap.parse_args()
    if os.environ.get(telemetry.DIR_ENV):
        # opt-in spans, same schema/dir layout as bench.py and the
        # cluster nodes (feed/wait comes from DataFeed when enabled)
        telemetry.configure(node_id="stress-fed", role="stress")
    if args.mode == "service-dynamic":
        with telemetry.span(f"stress_fed/{args.mode}",
                            trainers=args.trainers,
                            slow_factor=args.slow_factor) as sp:
            r = run_service_dynamic(trainers=args.trainers,
                                    slow_factor=args.slow_factor)
            sp.add(straggler_ratio=r["straggler_ratio"],
                   straggler_speedup=r["straggler_speedup"])
        print(json.dumps(r), flush=True)
        telemetry.flush()
        return
    if args.mode == "pipeline":
        results = []
        for m in ("fed784", "pipeline784"):
            with telemetry.span(f"stress_fed/{m}", batch=args.batch,
                                width=args.width, steps=args.steps) as sp:
                r = run_f784(m, args.batch, args.width, args.steps)
                if "records_per_sec" in r:
                    sp.add(records_per_sec=r["records_per_sec"])
            print(json.dumps(r), flush=True)
            results.append(r)
        if all("records_per_sec" in r for r in results):
            a, b = (results[0]["records_per_sec"],
                    results[1]["records_per_sec"])
            if a:
                print(json.dumps({"pipeline_vs_fed": round(b / a, 2)}),
                      flush=True)
        telemetry.flush()
        return
    modes = (["rows", "columnar"] if args.mode == "both" else [args.mode])
    results = []
    for m in modes:
        with telemetry.span(f"stress_fed/{m}", batch=args.batch,
                            image=args.image, steps=args.steps) as sp:
            r = run_mode(m, args.batch, args.image, args.steps)
            if "records_per_sec" in r:
                sp.add(records_per_sec=r["records_per_sec"])
        print(json.dumps(r), flush=True)
        results.append(r)
    if len(results) == 2 and all("records_per_sec" in r for r in results):
        a, b = results[0]["records_per_sec"], results[1]["records_per_sec"]
        if a:
            print(json.dumps({"columnar_speedup": round(b / a, 2)}),
                  flush=True)
    telemetry.flush()


if __name__ == "__main__":
    main()
