#!/bin/bash
# Round-4 follow-up chip session (v2, after the second relay death):
# everything still unmeasured, cheapest-and-most-informative first.
# Probe-gated like tpu_perf_session.sh; each step its own process
# (serialized claims) under scripts/with_tunnel_watchdog.sh, which
# kills the step within ~1 min of the relay dying (rc 86, session
# aborts) instead of burning the step's full timeout budget.
#
#   1. Roofline (chained-timing rewrite) -> ROOFLINE.json
#   2. ResNet sweep over fused-BN(+ReLU/+add+ReLU) configs, promote
#      (b256_s2d_bnf measured 99.2ms pre-bn_relu: direct A/B)
#   3. Analytic traffic floor vs measured roofline -> TRAFFIC.json
#   4. Re-profile the winner -> PERF_BREAKDOWN.md
#   5. Transformer selective-remat subset (rdots/b96), promote
#   6. bench.py -> the round's JSON line with promoted configs
set -uo pipefail
cd "$(dirname "$0")/.."

log=${TFOS_PERF_LOG:-perf_followup_r4.log}
echo "== r4 follow-up session v2 $(date -u +%FT%TZ) ==" | tee -a "$log"

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/tfos_xla_cache}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

run() {  # run <timeout_s> cmd... ; aborts the session if the relay died
  local tmo=$1; shift
  echo "-- $* (watchdog ${tmo}s) --" | tee -a "$log"
  bash scripts/with_tunnel_watchdog.sh "$tmo" "$@" 2>&1 | tee -a "$log"
  local rc=${PIPESTATUS[0]}
  echo "-- rc=$rc --" | tee -a "$log"
  if [ "$rc" = "86" ]; then
    echo "ABORT: relay died mid-step; nothing in the VM can restart it" \
      | tee -a "$log"
    exit 86
  fi
  if [ "$rc" = "127" ] || [ "$rc" = "126" ]; then
    echo "ABORT: step harness missing/not executable (rc=$rc) - a" \
         "broken checkout must not silently burn the chip window" \
      | tee -a "$log"
    exit "$rc"
  fi
}

echo "-- tpu_probe --" | tee -a "$log"
timeout "${TFOS_SESSION_PROBE_TIMEOUT:-300}" python scripts/tpu_probe.py 2>&1 | tee -a "$log"
probe_rc=${PIPESTATUS[0]}
echo "-- rc=$probe_rc --" | tee -a "$log"
if [ "$probe_rc" != "0" ]; then
  echo "ABORT: TPU probe failed (rc=$probe_rc) - tunnel/pool sick" | tee -a "$log"
  exit "$probe_rc"
fi

run 1800 python scripts/roofline.py --out ROOFLINE.json
TFOS_SWEEP=b256_s2d_bnf,b384_s2d_bnf,b256_s2d \
  run 7200 python scripts/sweep_resnet.py --steps 20 --image 224 --promote
run 600 python scripts/resnet_traffic.py --batch 256 --out TRAFFIC.json
run 3600 python scripts/profile_resnet.py --out PERF_BREAKDOWN.md \
    --steps 10 --image 224 $(python scripts/promoted_profile_args.py)
TFOS_SWEEP=b64_q512_kv512_rdots_pbwd,b96_q512_kv512_rdots_pbwd,b96_q512_kv512_remat_pbwd \
  run 7200 python scripts/sweep_transformer.py --steps 8 --promote
run 7200 python bench.py

echo "== done; promoted config: ==" | tee -a "$log"
cat "${TFOS_BENCH_CONFIG:-bench_config.json}" 2>/dev/null | tee -a "$log" || true
