#!/bin/bash
# Round-4 follow-up chip session (v2, after the second relay death):
# everything still unmeasured, cheapest-and-most-informative first.
# Probe-gated like tpu_perf_session.sh; each step its own process
# (serialized claims) wrapped in `timeout` (a compile request against a
# dying helper once wedged 47 min).
#
#   1. Roofline (chained-timing rewrite) -> ROOFLINE.json
#   2. ResNet sweep over fused-BN(+ReLU) configs, promote
#      (b256_s2d_bnf measured 99.2ms pre-bn_relu: direct A/B)
#   3. Analytic traffic floor vs measured roofline -> TRAFFIC.json
#   4. Re-profile the winner -> PERF_BREAKDOWN.md
#   5. Transformer selective-remat subset (rdots/b96), promote
#   6. bench.py -> the round's JSON line with promoted configs
set -uo pipefail
cd "$(dirname "$0")/.."

log=${TFOS_PERF_LOG:-perf_followup_r4.log}
echo "== r4 follow-up session v2 $(date -u +%FT%TZ) ==" | tee -a "$log"

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/tfos_xla_cache}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

run() {
  echo "-- $* --" | tee -a "$log"
  "$@" 2>&1 | tee -a "$log"
  echo "-- rc=$? --" | tee -a "$log"
}

echo "-- tpu_probe --" | tee -a "$log"
timeout "${TFOS_SESSION_PROBE_TIMEOUT:-300}" python scripts/tpu_probe.py 2>&1 | tee -a "$log"
probe_rc=${PIPESTATUS[0]}
echo "-- rc=$probe_rc --" | tee -a "$log"
if [ "$probe_rc" != "0" ]; then
  echo "ABORT: TPU probe failed (rc=$probe_rc) - tunnel/pool sick" | tee -a "$log"
  exit "$probe_rc"
fi

run timeout 1800 python scripts/roofline.py --out ROOFLINE.json
TFOS_SWEEP=b256_s2d_bnf,b384_s2d_bnf,b256_s2d \
  run timeout 7200 python scripts/sweep_resnet.py --steps 20 --image 224 --promote
run timeout 600 python scripts/resnet_traffic.py --batch 256 --out TRAFFIC.json
run timeout 3600 python scripts/profile_resnet.py --out PERF_BREAKDOWN.md \
    --steps 10 --image 224 $(python scripts/promoted_profile_args.py)
TFOS_SWEEP=b64_q512_kv512_rdots_pbwd,b96_q512_kv512_rdots_pbwd,b96_q512_kv512_remat_pbwd \
  run timeout 7200 python scripts/sweep_transformer.py --steps 8 --promote
run timeout 7200 python bench.py

echo "== done; promoted config: ==" | tee -a "$log"
cat "${TFOS_BENCH_CONFIG:-bench_config.json}" 2>/dev/null | tee -a "$log" || true
