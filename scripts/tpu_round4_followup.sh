#!/bin/bash
# Round-4 follow-up chip session: everything the first session's death
# left unmeasured, most valuable first.  Probe-gated like
# tpu_perf_session.sh; each step its own process (serialized claims).
#
#   1. ResNet sweep over the fused-BN configs, promote
#   2. Re-profile the (possibly new) winner -> PERF_BREAKDOWN.md
#   3. Transformer follow-up subset (pallas-bwd variants), promote
#   4. Roofline probe -> ROOFLINE.json (measured MXU + HBM ceilings)
#   5. bench.py -> the round's JSON line with promoted configs
set -uo pipefail
cd "$(dirname "$0")/.."

log=${TFOS_PERF_LOG:-perf_followup_r4.log}
echo "== r4 follow-up session $(date -u +%FT%TZ) ==" | tee -a "$log"

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/tfos_xla_cache}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

run() {
  echo "-- $* --" | tee -a "$log"
  "$@" 2>&1 | tee -a "$log"
  echo "-- rc=$? --" | tee -a "$log"
}

echo "-- tpu_probe --" | tee -a "$log"
timeout "${TFOS_SESSION_PROBE_TIMEOUT:-300}" python scripts/tpu_probe.py 2>&1 | tee -a "$log"
probe_rc=${PIPESTATUS[0]}
echo "-- rc=$probe_rc --" | tee -a "$log"
if [ "$probe_rc" != "0" ]; then
  echo "ABORT: TPU probe failed (rc=$probe_rc) - tunnel/pool sick" | tee -a "$log"
  exit "$probe_rc"
fi

# per-config timeout: the first session lost 47 min to a compile request
# against a dying helper; timeout the WHOLE step rather than wedge
TFOS_SWEEP=b256_s2d_bnf,b512_s2d_bnf,b384_s2d_bnf \
  run timeout 7200 python scripts/sweep_resnet.py --steps 20 --image 224 --promote
run timeout 3600 python scripts/profile_resnet.py --out PERF_BREAKDOWN.md \
    --steps 10 --image 224 $(python scripts/promoted_profile_args.py)
TFOS_SWEEP=b64_q512_kv512_remat_pbwd,b32_q1024_kv1024_remat_pbwd,b64_q512_kv512_remat_pbwd_bce,b32_q512_kv512_remat_pbwd_bce \
  run timeout 7200 python scripts/sweep_transformer.py --steps 8 --promote
run timeout 1800 python scripts/roofline.py --out ROOFLINE.json
run timeout 7200 python bench.py

echo "== done; promoted config: ==" | tee -a "$log"
cat "${TFOS_BENCH_CONFIG:-bench_config.json}" 2>/dev/null | tee -a "$log" || true
