#!/bin/bash
# Round-4 follow-up chip session (v2, after the second relay death):
# everything still unmeasured, cheapest-and-most-informative first.
# Probe-gated; each step its own process (serialized claims) under the
# tunnel watchdog via _session_lib.sh (see tpu_perf_session.sh for the
# failure semantics).
#
#   1. Roofline (chained-timing rewrite) -> ROOFLINE.json
#   2. ResNet sweep over fused-BN(+ReLU/+add+ReLU) configs, promote
#      (b256_s2d_bnf measured 99.2ms pre-bn_relu: direct A/B)
#   3. Analytic traffic floor vs measured roofline -> TRAFFIC.json
#   4. fwd/grad step decomposition of the winner (no profiler needed)
#   5. Re-profile the winner -> PERF_BREAKDOWN.md
#   6. Transformer selective-remat subset (rdots/b96), promote
#   7. bench.py -> the round's JSON line with promoted configs
set -uo pipefail
cd "$(dirname "$0")/.."

log=${TFOS_PERF_LOG:-perf_followup_r4.log}
echo "== r4 follow-up session v2 $(date -u +%FT%TZ) ==" | tee -a "$log"
source scripts/_session_lib.sh

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/tfos_xla_cache}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

probe_gate

session_run 1800 python scripts/roofline.py --out ROOFLINE.json
TFOS_SWEEP=b256_s2d_bnf,b384_s2d_bnf,b256_s2d \
  session_run 7200 python scripts/sweep_resnet.py --steps 20 --image 224 --promote
host_run 600 python scripts/resnet_traffic.py --batch 256 --out TRAFFIC.json
# step decomposition of the winner config: train - grad = optimizer,
# grad - fwd = backward (one claim each, no profiler)
TFOS_SWEEP=b256_s2d_bnf TFOS_SWEEP_MODE=fwd \
  session_run 3600 python scripts/sweep_resnet.py --steps 20 --image 224
TFOS_SWEEP=b256_s2d_bnf TFOS_SWEEP_MODE=grad \
  session_run 3600 python scripts/sweep_resnet.py --steps 20 --image 224
session_run 3600 python scripts/profile_resnet.py --out PERF_BREAKDOWN.md \
    --steps 10 --image 224 $(python scripts/promoted_profile_args.py)
TFOS_SWEEP=b64_q512_kv512_rdots_pbwd,b96_q512_kv512_rdots_pbwd,b96_q512_kv512_remat_pbwd \
  session_run 7200 python scripts/sweep_transformer.py --steps 8 --promote
session_run 7200 python bench.py

echo "== done; promoted config: ==" | tee -a "$log"
cat "${TFOS_BENCH_CONFIG:-bench_config.json}" 2>/dev/null | tee -a "$log" || true
