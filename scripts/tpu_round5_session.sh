#!/bin/bash
# Round-5 chip session — VERDICT r4 order: durable evidence first, then
# measurement, then decisions.  Probe-gated; each step its own process
# (serialized claims) under the tunnel watchdog via _session_lib.sh.
#
#   1. bench.py -> BENCH_session_r5.json: the durable corrected-
#      convention line (VERDICT #3) with fed vs_transfer_ceiling
#      recorded (VERDICT #4) — FIRST minutes of chip contact, before
#      any sweep can die and take the session with it.
#   2. Roofline (chained-probe rewrite) -> ROOFLINE.json (VERDICT #6):
#      the measured HBM/MXU floors that aim the structural ResNet work.
#   3. fwd/grad step decomposition of the promoted ResNet config
#      (train - grad = optimizer, grad - fwd = backward).
#   4. ResNet A/Bs: r4's pending BN-fusion family + round-5 structural
#      candidates (TFOS_SESSION_RESNET_SWEEP below), promote.
#   5. Analytic traffic floor vs measured roofline -> TRAFFIC.json.
#   6. Re-profile the winner -> PERF_BREAKDOWN.md.
#   7. Transformer: rdots selective-remat subset + long-seq blockwise-CE
#      configs (VERDICT #8), promote.
#   8. Final bench.py -> BENCH_session_r5_final.json with whatever got
#      promoted above.
set -uo pipefail
cd "$(dirname "$0")/.."

log=${TFOS_PERF_LOG:-perf_session_r5.log}
echo "== r5 session $(date -u +%FT%TZ) ==" | tee -a "$log"
source scripts/_session_lib.sh

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/tfos_xla_cache}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

smoke=${TFOS_SESSION_SMOKE:-0}
profile_extra=""
roofline_out=ROOFLINE.json
traffic_out=TRAFFIC.json
if [ "$smoke" = "1" ]; then
  export TFOS_SWEEP_SMOKE=1
  profile_extra="--batch 4"
  # smoke runs must never clobber the real chip evidence at the repo
  # root with CPU numbers (resnet_traffic's physics guard only rejects
  # values ABOVE the ceilings, not a CPU-platform roofline)
  roofline_out=$(mktemp -u /tmp/tfos_smoke_roofline.XXXX.json)
  traffic_out=$(mktemp -u /tmp/tfos_smoke_traffic.XXXX.json)
  echo "(smoke mode: tiny shapes, no promote, benches skipped," \
       "roofline/traffic -> /tmp)" | tee -a "$log"
else
  probe_gate
fi

rsteps=${TFOS_SESSION_RESNET_STEPS:-20}
image=${TFOS_SESSION_IMAGE:-224}
tsteps=${TFOS_SESSION_TRANSFORMER_STEPS:-8}

# -- 1. durable corrected-convention bench line, before anything else --
if [ "$smoke" = "1" ]; then
  echo "-- bench.py skipped (smoke mode) --" | tee -a "$log"
else
  # serve + decode lanes are CPU-forced (claim-safe alongside the TPU
  # claim this step holds); TFOS_BENCH_SERVE=0 / TFOS_BENCH_DECODE=0
  # to skip
  # watchtower observe-only: the durable line's "health" block records
  # anomalies seen during the lanes but never halts the unattended round
  TFOS_BENCH_SERVE="${TFOS_BENCH_SERVE:-1}" \
  TFOS_BENCH_ELASTIC_SERVE="${TFOS_BENCH_ELASTIC_SERVE:-1}" \
  TFOS_BENCH_DECODE="${TFOS_BENCH_DECODE:-1}" \
  TFOS_BENCH_DECODE_PREFIX="${TFOS_BENCH_DECODE_PREFIX:-0.6}" \
  TFOS_HEALTH_ACTION="${TFOS_HEALTH_ACTION:-none}" \
  TFOS_HEALTH_GRADNORM="${TFOS_HEALTH_GRADNORM:-0}" \
    session_run 7200 bash -c 'python bench.py > BENCH_session_r5.json.tmp \
    && mv BENCH_session_r5.json.tmp BENCH_session_r5.json \
    && cat BENCH_session_r5.json'
fi

# -- 2. measured roofline (fixed script; stale artifact was deleted) --
session_run 1800 python scripts/roofline.py --out "$roofline_out"

# -- 3. step decomposition of the promoted config ----------------------
decomp=${TFOS_SESSION_DECOMP:-b256_s2d_bnf}
TFOS_SWEEP="$decomp" TFOS_SWEEP_MODE=fwd \
  session_run 3600 python scripts/sweep_resnet.py --steps "$rsteps" --image "$image"
TFOS_SWEEP="$decomp" TFOS_SWEEP_MODE=grad \
  session_run 3600 python scripts/sweep_resnet.py --steps "$rsteps" --image "$image"

# -- 4. ResNet A/Bs: pending BN family + structural candidates ---------
# b256_s2d_bnf re-anchors against r4's 99.2ms; b128/b192 probe the
# batch-capacity hypothesis; b256_s2d_remat_bnf re-tests remat with the
# fused-BN backward; b256_s2d closes the bn_relu-fusion A/B
TFOS_SWEEP="${TFOS_SESSION_RESNET_SWEEP:-b256_s2d_bnf,b128_s2d_bnf,b192_s2d_bnf,b256_s2d_remat_bnf,b256_s2d}" \
  session_run 7200 python scripts/sweep_resnet.py --steps "$rsteps" --image "$image" --promote

# -- 5. analytic floor against the measured roofline -------------------
host_run 600 python scripts/resnet_traffic.py --batch 256 \
    --roofline "$roofline_out" --out "$traffic_out"

# -- 6. where the winner's time goes -----------------------------------
session_run 3600 python scripts/profile_resnet.py \
    --out "${TFOS_SESSION_BREAKDOWN:-PERF_BREAKDOWN.md}" \
    --steps 10 --image "$image" $(python scripts/promoted_profile_args.py) \
    $profile_extra

# -- 7. transformer: rdots + long-seq blockwise CE ---------------------
TFOS_SWEEP="${TFOS_SESSION_TRANSFORMER_SWEEP:-b64_q512_kv512_rdots_pbwd,b96_q512_kv512_rdots_pbwd,b96_q512_kv512_remat_pbwd,b16_s4096_remat_pbwd_bce,b16_s4096_remat_pbwd,b32_s4096_remat_pbwd_bce}" \
  session_run 7200 python scripts/sweep_transformer.py --steps "$tsteps" --promote

# -- 8. final bench with everything promoted ---------------------------
if [ "$smoke" = "1" ]; then
  echo "-- final bench.py skipped (smoke mode) --" | tee -a "$log"
else
  TFOS_BENCH_SERVE="${TFOS_BENCH_SERVE:-1}" \
  TFOS_BENCH_ELASTIC_SERVE="${TFOS_BENCH_ELASTIC_SERVE:-1}" \
  TFOS_BENCH_DECODE="${TFOS_BENCH_DECODE:-1}" \
  TFOS_BENCH_DECODE_PREFIX="${TFOS_BENCH_DECODE_PREFIX:-0.6}" \
  TFOS_HEALTH_ACTION="${TFOS_HEALTH_ACTION:-none}" \
  TFOS_HEALTH_GRADNORM="${TFOS_HEALTH_GRADNORM:-0}" \
    session_run 7200 bash -c 'python bench.py > BENCH_session_r5_final.json.tmp \
    && mv BENCH_session_r5_final.json.tmp BENCH_session_r5_final.json \
    && cat BENCH_session_r5_final.json'
fi
# perf-regression gate: newest BENCH line vs prior round (host-side,
# no TPU claim; host_run never aborts the session on a red verdict)
host_run 120 python scripts/bench_check.py

echo "== done; promoted config: ==" | tee -a "$log"
cat "${TFOS_BENCH_CONFIG:-bench_config.json}" 2>/dev/null | tee -a "$log" || true
