"""Profile the ResNet-50 train step on the attached TPU and print the
top ops by self-time, grouped by fusion kind.

Usage: python scripts/profile_resnet.py [--steps N] [--batch N]
Writes the xplane trace under /tmp/tfos_profile and parses it with the
tensorflow xplane protobuf (no TensorBoard needed).
"""

import argparse
import glob
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def parse_xplane(logdir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        raise SystemExit(f"no xplane.pb under {logdir}")
    xspace = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xspace.ParseFromString(f.read())
    return xspace


def summarize(xspace, top=40):
    # find the TPU device plane (op-level events live there)
    for plane in xspace.planes:
        if "TPU" in plane.name or "/device:" in plane.name:
            ev_names = plane.event_metadata
            totals = defaultdict(float)
            counts = defaultdict(int)
            for line in plane.lines:
                if "XLA Ops" not in line.name and "Ops" != line.name.strip():
                    continue
                for ev in line.events:
                    name = ev_names[ev.metadata_id].name
                    totals[name] += ev.duration_ps / 1e9  # ms
                    counts[name] += 1
            if totals:
                yield plane.name, totals, counts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--stem", choices=("s2d", "7x7"), default="s2d")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--bn", choices=("fused", "plain"), default="fused",
                    help="BatchNorm backward: custom-VJP fused vs autodiff")
    ap.add_argument("--out", default=None,
                    help="also write the breakdown as markdown (e.g. PERF.md)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from tensorflowonspark_tpu.models import resnet

    # one jitted init program: eager init is hundreds of tiny dispatches,
    # intolerably slow over a remote-compile TPU tunnel
    print("init...", flush=True)
    opt = optax.sgd(0.1, momentum=0.9)

    @jax.jit
    def init_all(key):
        params, state = resnet.init(key, depth=50, num_classes=1000)
        return params, state, opt.init(params)

    params, state, opt_state = init_all(jax.random.PRNGKey(0))
    # apply() silently falls back to 7x7 on odd image sizes — report the
    # stem that actually runs, not the one requested
    effective_stem = ("s2d" if args.stem == "s2d" and args.image % 2 == 0
                      else "7x7")
    step_fn = resnet.make_train_step(opt, depth=50,
                                     stem_s2d=(args.stem == "s2d"),
                                     remat=args.remat,
                                     bn_fused=(args.bn == "fused"))

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.random((args.batch, args.image, args.image, 3),
                                    dtype=np.float32), dtype=jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, args.batch), dtype=jnp.int32)

    @jax.jit
    def run_steps(params, state, opt_state, images, labels):
        def body(carry, _):
            p, s, o = carry
            p, s, o, loss, _ = step_fn(p, s, o, images, labels)
            return (p, s, o), loss
        (_, _, _), losses = lax.scan(body, (params, state, opt_state),
                                     None, length=args.steps)
        return losses[-1]

    print("compiling...", flush=True)
    float(run_steps(params, state, opt_state, images, labels))
    t0 = time.perf_counter()
    float(run_steps(params, state, opt_state, images, labels))
    dt = time.perf_counter() - t0
    ms_per_step = 1000 * dt / args.steps
    print(f"step={ms_per_step:.1f}ms  img/s={args.batch / (dt / args.steps):.0f}",
          flush=True)

    import shutil

    logdir = "/tmp/tfos_profile"
    shutil.rmtree(logdir, ignore_errors=True)
    jax.profiler.start_trace(logdir)
    float(run_steps(params, state, opt_state, images, labels))
    jax.profiler.stop_trace()

    xspace = parse_xplane(logdir)
    report = [f"# ResNet-50 step-time breakdown",
              f"",
              f"batch={args.batch} image={args.image} stem={effective_stem} "
              f"remat={args.remat} bn={args.bn} steps={args.steps}; "
              f"measured {ms_per_step:.1f} ms/step "
              f"({args.batch / (ms_per_step / 1000):.0f} img/s).",
              ""]
    for plane_name, totals, counts in summarize(xspace):
        total = sum(totals.values())
        print(f"\n== {plane_name}  total {total:.1f}ms over {args.steps} steps ==")
        report += [f"## {plane_name} — {total:.1f} ms device time "
                   f"over {args.steps} steps", ""]
        # group by fusion-kind prefix
        groups = defaultdict(float)
        for name, ms in totals.items():
            key = name.split(".")[0].split("_")[0]
            groups[key] += ms
        report += ["| op group | ms | % |", "|---|---|---|"]
        for k, v in sorted(groups.items(), key=lambda kv: -kv[1])[:15]:
            print(f"  [group] {k:30s} {v:8.2f}ms {100 * v / total:5.1f}%")
            report.append(f"| {k} | {v:.2f} | {100 * v / total:.1f} |")
        print()
        report += ["", "| top op | ms | n | % |", "|---|---|---|---|"]
        for name, ms in sorted(totals.items(), key=lambda kv: -kv[1])[:40]:
            print(f"  {ms:8.2f}ms x{counts[name]:<4d} {100 * ms / total:5.1f}%  {name[:110]}")
            report.append(f"| `{name[:90]}` | {ms:.2f} | {counts[name]} "
                          f"| {100 * ms / total:.1f} |")
        report.append("")
    if args.out:
        from tensorflowonspark_tpu.recordio import fs as _fs

        with _fs.open_file(args.out, "w") as f:
            f.write("\n".join(report) + "\n")
        print(f"\nwrote {args.out}")


if __name__ == "__main__":
    main()
