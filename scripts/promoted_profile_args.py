"""Emit profile_resnet.py CLI args for the promoted bench config (single
source of truth: bench.bench_config_path / bench._promoted_config).
Used by tpu_perf_session.sh so the shell never re-implements the config
path resolution."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import bench

    cfg = bench._promoted_config()
    args = []
    if cfg.get("batch"):
        args += ["--batch", str(cfg["batch"])]
    if not cfg.get("stem_s2d", True):
        args += ["--stem", "7x7"]
    if cfg.get("remat"):
        args += ["--remat"]
    if not cfg.get("bn_fused", False):
        # absent key = the promoted winner was measured with plain BN (or
        # never measured): profiling must not debut the fused graph on TPU
        args += ["--bn", "plain"]
    print(" ".join(args))


if __name__ == "__main__":
    main()
