"""TPU VM cluster launcher (parity: reference scripts/spark_ec2.py — the
cluster-bringup utility; that one provisioned EC2 + Spark Standalone,
this one provisions a GCP TPU pod slice + the framework's rendezvous).

Requires ``gcloud`` and network access; in an egress-free environment
every action is printed as a dry run (--dry_run is implied when gcloud
is absent), so the exact commands remain auditable.

    python scripts/tpu_launch.py create  --name tfos --zone us-central2-b \\
        --accelerator v5litepod-16
    python scripts/tpu_launch.py run     --name tfos -- python train.py
    python scripts/tpu_launch.py delete  --name tfos
"""

from __future__ import annotations

import argparse
import os
import shlex
import shutil
import subprocess
import sys

SETUP = (
    "cd /opt/tfos && pip install -e . && "
    "python -c 'import tensorflowonspark_tpu'"
)


def gcloud_available():
    return shutil.which("gcloud") is not None


def _run(cmd, dry):
    print("+ " + " ".join(shlex.quote(c) for c in cmd))
    if dry:
        return 0
    return subprocess.call(cmd)


def cmd_create(args, dry):
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "create", args.name,
        "--zone", args.zone,
        "--accelerator-type", args.accelerator,
        "--version", args.runtime_version,
    ]
    rc = _run(cmd, dry)
    if rc == 0 and args.setup:
        # ship the source tree, then install it on every worker
        rc = cmd_ssh_all(args, dry, "sudo mkdir -p /opt/tfos && "
                                    "sudo chown $USER /opt/tfos")
        if rc == 0:
            repo_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            rc = _run([
                "gcloud", "compute", "tpus", "tpu-vm", "scp", "--recurse",
                f"{repo_root}/", f"{args.name}:/opt/tfos",
                "--zone", args.zone, "--worker=all",
            ], dry)
        if rc == 0:
            rc = cmd_ssh_all(args, dry, SETUP)
    return rc


def cmd_ssh_all(args, dry, command):
    return _run([
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", args.name,
        "--zone", args.zone, "--worker=all", "--command", command,
    ], dry)


def cmd_run(args, dry):
    # every host runs the same driver command; the framework's rendezvous
    # (TFOS_SERVER_HOST/PORT point workers at the server, reservation
    # parity reservation.py:25-26) assembles them into one cluster
    extra = " ".join(shlex.quote(c) for c in args.command)
    return cmd_ssh_all(args, dry, extra)


def cmd_delete(args, dry):
    return _run([
        "gcloud", "compute", "tpus", "tpu-vm", "delete", args.name,
        "--zone", args.zone, "--quiet",
    ], dry)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("action", choices=["create", "run", "delete"])
    p.add_argument("--name", required=True)
    p.add_argument("--zone", default="us-central2-b")
    p.add_argument("--accelerator", default="v5litepod-16")
    p.add_argument("--runtime_version", default="tpu-ubuntu2204-base")
    p.add_argument("--setup", action="store_true",
                   help="pip-install the framework on every worker after create")
    p.add_argument("--dry_run", action="store_true")
    p.add_argument("command", nargs="*", help="command for `run` (after --)")
    args = p.parse_args(argv)

    dry = args.dry_run or not gcloud_available()
    if dry and not args.dry_run:
        print("gcloud not found — dry run only", file=sys.stderr)
    return {
        "create": cmd_create, "run": cmd_run, "delete": cmd_delete,
    }[args.action](args, dry)


if __name__ == "__main__":
    sys.exit(main())
