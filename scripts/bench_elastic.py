"""Elastic-runtime bench driver: one JSON line on stdout.

Run by bench.py's ``elastic`` lane in a SUBPROCESS with a scrubbed env
(``PYTHONPATH= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_
device_count=8``): the lane is host/CPU-only by construction, so it is
safe alongside a TPU claim (the tunnel serializes claims — CLAUDE.md).
A real file because the engine's spawn start method cannot import
heredoc drivers.

Measures the elastic hot path on fake CPU devices: build an 8-virtual
mesh, shard the mnist train state, resize 8 -> 4 physical (accum x2),
reshard the live state, and resume a checkpoint cross-mesh through
``restore_any(target_shardings=...)`` (docs/elastic.md).
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu import elastic
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    devices = jax.devices()
    if len(devices) < 8:
        print(json.dumps({"error": f"need 8 fake devices, "
                                   f"got {len(devices)}"}))
        return 1

    spec = elastic.TrainSpec({"data": 8}, global_batch=256)
    t0 = time.perf_counter()
    rt = elastic.ElasticRuntime(spec, devices=devices[:8])
    build_ms = (time.perf_counter() - t0) * 1e3

    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(1e-2)
    opt_state = opt.init(params)
    (params, state, opt_state), _ = rt.shard_train_state(
        params, {"step": jnp.zeros((), jnp.int32)}, opt_state)

    tmp = tempfile.mkdtemp(prefix="tfos_bench_elastic_")
    try:
        ckpt.save_checkpoint(tmp, params, step=7)

        t0 = time.perf_counter()
        rt.resize(devices=devices[:4])
        resize_ms = (time.perf_counter() - t0) * 1e3

        t0 = time.perf_counter()
        (params, state, opt_state), _ = rt.reshard_train_state(
            params, state, opt_state)
        jax.block_until_ready(params)
        reshard_ms = (time.perf_counter() - t0) * 1e3

        t0 = time.perf_counter()
        restored, step = rt.restore(tmp)
        jax.block_until_ready(restored)
        restore_ms = (time.perf_counter() - t0) * 1e3

        sched = rt.batch_schedule()
        print(json.dumps({
            "build_ms": round(build_ms, 2),
            "resize_ms": round(resize_ms, 2),
            "reshard_ms": round(reshard_ms, 2),
            "restore_ms": round(restore_ms, 2),
            "restored_step": int(step),
            "accum_steps": sched["accum_steps"],
            "microbatch": sched["microbatch"],
            "devices": rt.layout.n_physical,
            "virtual_devices": rt.layout.n_virtual,
        }))
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
