"""Analytic HBM-traffic floor for a ResNet train step → achievable MFU.

The measured ResNet-50 step is HBM-bound (PERF.md round 4), so the honest
performance ceiling is set by unavoidable memory traffic, not the MXU
datasheet.  This walks the real stage plan from models/resnet.py and
counts, per conv, the traffic an *ideally fused* training step must move:

  fwd:        read x, read w, write y            (BN/ReLU fused for free)
  bwd-data:   read dy, read w, write dx
  bwd-filter: read dy, read x, write dw

i.e. 3*(|x|+|y|) activation bytes + 3*|w| weight bytes per conv, in the
compute dtype, plus the optimizer pass over the f32 master params
(SGD+momentum: read param/momentum/grad, write param/momentum — 20B per
parameter, conv + BN + FC head).  Dividing by a measured elementwise
bandwidth (from scripts/roofline.py → ROOFLINE.json) gives a
lower-bound step time and therefore an upper bound on achievable MFU
for this model shape — the number `resnet50_train_mfu` should be
judged against, alongside the datasheet-peak MFU.  The floors are also
split fwd/bwd/optimizer to score the on-chip TFOS_SWEEP_MODE=fwd|grad
decomposition phase by phase.  (Pre-r5 TRAFFIC/PERF history quotes
33.6 GB for b256 — the same model without the 0.51 GB optimizer pass.)

Usage:
  python scripts/resnet_traffic.py [--batch 256] [--image 224]
      [--roofline ROOFLINE.json] [--step-ms 99.2] [--out TRAFFIC.json]

Pure host-side arithmetic — no jax, safe anywhere.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (block kind, per-stage counts) — mirror models/resnet.py _PLANS
_PLANS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}


def conv_cost(n, h_in, w_in, c_in, c_out, k, stride, bytes_per):
    """Per-conv ideally-fused train-step costs, split by phase so the
    on-chip TFOS_SWEEP_MODE=fwd|grad decomposition (sweep_resnet.py)
    can be scored against the model phase by phase:

      fwd:  read x + read w, write y           (1x MACs)
      bwd:  dgrad (read dy, w; write dx) +
            wgrad (read dy, x; write dw)       (2x MACs)

    Returns (fwd_act, bwd_act, n_weight_elems, fwd_flops, bwd_flops, hw).
    """
    h_out, w_out = h_in // stride, w_in // stride
    x = n * h_in * w_in * c_in
    y = n * h_out * w_out * c_out
    w = k * k * c_in * c_out
    fwd_act = (x + y) * bytes_per
    bwd_act = 2 * (x + y) * bytes_per
    macs2 = 2 * n * h_out * w_out * k * k * c_in * c_out
    return fwd_act, bwd_act, w, macs2, 2 * macs2, (h_out, w_out)


def resnet_traffic(depth=50, batch=256, image=224, width=64, bytes_per=2,
                   stem_s2d=True, num_classes=1000):
    kind, counts = _PLANS[depth]
    fwd_act = bwd_act = total_w = fwd_flops = bwd_flops = 0
    n_params = 0  # conv kernels + the 2 BN params following each conv
    n = batch

    def add(r, bn_ch=0):
        nonlocal fwd_act, bwd_act, total_w, fwd_flops, bwd_flops, n_params
        fa, ba, w_elems, ff, bf, hw = r
        fwd_act += fa
        bwd_act += ba
        # weight traffic: fwd read + dgrad read + dw write = 3 passes
        total_w += 3 * w_elems * bytes_per
        fwd_flops += ff
        bwd_flops += bf
        n_params += w_elems + 2 * bn_ch
        return hw

    # stem: 7x7/s2 (or the exact-equivalent 4x4/s1 over 2x2 s2d input —
    # same output, slightly different input traffic; use s2d's)
    if stem_s2d:
        hw = add(conv_cost(n, image // 2, image // 2, 12, width, 4, 1,
                           bytes_per), bn_ch=width)
    else:
        hw = add(conv_cost(n, image, image, 3, width, 7, 2, bytes_per),
                 bn_ch=width)
    h, w_ = hw[0] // 2, hw[1] // 2  # 3x3/s2 maxpool
    in_ch = width
    for stage, nblocks in enumerate(counts):
        ch = width * (2 ** stage)
        for b in range(nblocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            if kind == "bottleneck":
                out_ch = ch * 4
                add(conv_cost(n, h, w_, in_ch, ch, 1, 1, bytes_per),
                    bn_ch=ch)
                hw = add(conv_cost(n, h, w_, ch, ch, 3, stride, bytes_per),
                         bn_ch=ch)
                add(conv_cost(n, hw[0], hw[1], ch, out_ch, 1, 1, bytes_per),
                    bn_ch=out_ch)
            else:
                out_ch = ch
                hw = add(conv_cost(n, h, w_, in_ch, ch, 3, stride, bytes_per),
                         bn_ch=ch)
                add(conv_cost(n, hw[0], hw[1], ch, ch, 3, 1, bytes_per),
                    bn_ch=ch)
            if stride != 1 or in_ch != out_ch:
                add(conv_cost(n, h, w_, in_ch, out_ch, 1, stride, bytes_per),
                    bn_ch=out_ch)
            h, w_ = hw
            in_ch = out_ch
    # FC head params (w + b) join the conv + BN count
    n_params += in_ch * num_classes + num_classes
    # optimizer pass (SGD+momentum over f32 master params): read param,
    # momentum, grad; write param, momentum — 5 x 4B per parameter.
    # Small next to activations, but the train-vs-grad decomposition
    # isolates exactly this, so model it.
    opt_bytes = 5 * 4 * n_params
    return {"act_bytes": fwd_act + bwd_act, "weight_bytes": total_w,
            "train_flops": fwd_flops + bwd_flops,
            "fwd_act_bytes": fwd_act, "bwd_act_bytes": bwd_act,
            "fwd_flops": fwd_flops, "bwd_flops": bwd_flops,
            "opt_bytes": opt_bytes}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--roofline", default="ROOFLINE.json")
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--step-ms", type=float, default=None,
                    help="measured step time to score against the floor")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    t = resnet_traffic(args.depth, args.batch, args.image)
    # whole-step traffic includes the optimizer pass so the headline
    # floor reconciles with fwd_floor + bwd_floor + opt_floor
    gb = (t["act_bytes"] + t["weight_bytes"] + t["opt_bytes"]) / 1e9

    hbm_gbs = None
    mxu_tflops = args.peak_tflops
    if os.path.exists(args.roofline):
        with open(args.roofline) as f:
            roof = json.load(f)
        # refuse numbers roofline.py marked timing-compromised, or that
        # exceed the physics limits roofline stamped into the report
        # (legacy files without a stamp get v5e-class defaults — the
        # source of truth is roofline.physics_limits)
        max_gbs = roof.get("sanity_max_gbs", 1600)
        max_tflops = roof.get("sanity_max_tflops", 400)
        if roof.get("suspect"):
            print(f"ignoring {args.roofline}: marked suspect "
                  f"{roof['suspect']} (timing path compromised)")
        elif roof.get("platform") not in (None, "tpu"):
            # a smoke/dev-box roofline (platform cpu) must not pose as
            # the chip floors: CPU GB/s are far BELOW the ceilings, so
            # the physics guard alone would accept them
            print(f"ignoring {args.roofline}: platform "
                  f"{roof.get('platform')!r} is not a TPU measurement")
        elif roof.get("elementwise_gbs", 0) > max_gbs \
                or roof.get("matmul_bf16_tflops", 0) > max_tflops:
            print(f"ignoring {args.roofline}: values exceed datasheet "
                  f"physics (stale dispatch-artifact measurement)")
        else:
            hbm_gbs = roof.get("elementwise_gbs")
            mxu_tflops = roof.get("matmul_bf16_tflops", args.peak_tflops)

    report = {"depth": args.depth, "batch": args.batch, "image": args.image,
              "min_hbm_gb_per_step": round(gb, 3),
              "train_tflops_per_step": round(t["train_flops"] / 1e12, 3)}
    print(f"ResNet-{args.depth} b{args.batch} im{args.image}: "
          f"minimum {gb:.2f} GB/step, {t['train_flops']/1e12:.2f} TFLOP/step")

    if hbm_gbs:
        def phase_floor(act_bytes, flops):
            """Per-phase lower bound: each phase is bounded by the
            slower of its own HBM traffic and its own MXU work."""
            h = act_bytes / 1e9 / hbm_gbs * 1e3
            m = flops / (mxu_tflops * 1e12) * 1e3
            return max(h, m), h, m

        # weights traffic: split 1/3 fwd, 2/3 bwd like the act model
        wb = t["weight_bytes"]
        fwd_ms, fwd_h, fwd_m = phase_floor(
            t["fwd_act_bytes"] + wb // 3, t["fwd_flops"])
        bwd_ms, bwd_h, bwd_m = phase_floor(
            t["bwd_act_bytes"] + 2 * wb // 3, t["bwd_flops"])
        opt_ms = t["opt_bytes"] / 1e9 / hbm_gbs * 1e3
        floor_ms = gb / hbm_gbs * 1e3
        mxu_ms = t["train_flops"] / (mxu_tflops * 1e12) * 1e3
        bound_ms = max(floor_ms, mxu_ms)
        mfu_ceiling = (t["train_flops"] / (args.peak_tflops * 1e12)) \
            / (bound_ms / 1e3)
        report.update({
            "hbm_gbs_measured": hbm_gbs,
            "hbm_floor_ms": round(floor_ms, 1),
            "mxu_floor_ms": round(mxu_ms, 1),
            "bound": "hbm" if floor_ms > mxu_ms else "mxu",
            "achievable_mfu_ceiling": round(mfu_ceiling, 4),
            # score these against TFOS_SWEEP_MODE=fwd|grad measurements:
            # measured fwd vs fwd_floor_ms; (grad - fwd) vs bwd_floor_ms;
            # (train - grad) vs opt_floor_ms
            "fwd_floor_ms": round(fwd_ms, 1),
            "bwd_floor_ms": round(bwd_ms, 1),
            "opt_floor_ms": round(opt_ms, 2),
            "fwd_bound": "hbm" if fwd_h > fwd_m else "mxu",
            "bwd_bound": "hbm" if bwd_h > bwd_m else "mxu",
        })
        print(f"floors: HBM {floor_ms:.1f} ms (at measured {hbm_gbs} GB/s), "
              f"MXU {mxu_ms:.1f} ms (at measured {mxu_tflops} TFLOP/s)")
        print(f"phase floors: fwd {fwd_ms:.1f} ms "
              f"({report['fwd_bound']}-bound), bwd {bwd_ms:.1f} ms "
              f"({report['bwd_bound']}-bound), optimizer {opt_ms:.2f} ms")
        print(f"achievable MFU ceiling (vs {args.peak_tflops} TFLOP/s "
              f"datasheet): {mfu_ceiling:.3f}")
        if args.step_ms:
            report["step_ms"] = args.step_ms
            report["pct_of_roofline"] = round(bound_ms / args.step_ms, 3)
            print(f"measured {args.step_ms} ms -> "
                  f"{100 * bound_ms / args.step_ms:.0f}% of roofline")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
