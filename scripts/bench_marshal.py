"""Measure native (_tfos_marshal) vs numpy row<->column marshalling.

Round-1/2 'done' criterion for native/marshal.c: a measured speedup over
the numpy path on a realistic batch (parity target: the reference's JVM
batch2tensors/tensors2batch, TFModel.scala:51-239, whose point is keeping
per-record conversion out of interpreted code).

Usage: python scripts/bench_marshal.py [--rows N] [--reps N]
Prints one table; no jax / no TPU involved.
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflowonspark_tpu.recordio import marshal  # noqa: E402


def timeit(fn, reps):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench(name, rows, spec, reps):
    ext = marshal._load_ext()
    assert ext is not None, "native extension missing"

    native_cols = None

    def run_native():
        nonlocal native_cols
        native_cols = ext.rows_to_columns(rows, [(c, int(w)) for c, w in spec])

    def run_numpy():
        out = []
        for c, (code, width) in enumerate(spec):
            vals = [r[c] for r in rows]
            out.append(np.asarray(vals, dtype=marshal._CODE_TO_DTYPE[code]))
        return tuple(out)

    t_nat = timeit(run_native, reps)
    t_np = timeit(run_numpy, reps)

    cols = native_cols
    t_nat_back = timeit(lambda: ext.columns_to_rows(list(cols)), reps)

    def back_numpy():
        lists = [a.tolist() if a.ndim <= 1 else [r.tolist() for r in a]
                 for a in cols]
        return [tuple(col[i] for col in lists) for i in range(len(rows))]

    t_np_back = timeit(back_numpy, reps)

    print(f"{name:34s} rows->cols  native {t_nat*1e3:7.2f}ms  "
          f"numpy {t_np*1e3:7.2f}ms  speedup {t_np/t_nat:5.2f}x")
    print(f"{'':34s} cols->rows  native {t_nat_back*1e3:7.2f}ms  "
          f"numpy {t_np_back*1e3:7.2f}ms  speedup {t_np_back/t_nat_back:5.2f}x")
    return t_np / t_nat, t_np_back / t_nat_back


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--reps", type=int, default=7)
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    n = args.rows

    # MNIST-pipeline shape: 784-wide float features + int label
    # (reference test_pipeline.py / mnist_spark.py feed rows)
    mnist = [(list(map(float, rng.random(784))), int(rng.integers(10)))
             for _ in range(n)]
    s1 = bench("mnist rows (784f list + label)", mnist,
               [("f", 784), ("l", 0)], args.reps)

    # scalar-heavy row: 14 mixed scalar columns (TFModel TestData shape)
    scal = [tuple([bool(i % 2)] + [int(i)] * 6 + [float(i)] * 7)
            for i in range(n)]
    s2 = bench("scalar rows (14 mixed cols)", scal,
               [("?", 0)] + [("l", 0)] * 6 + [("d", 0)] * 7, args.reps)

    # inference batch: 64-wide double vectors
    infer = [(list(map(float, rng.random(64))),) for _ in range(n)]
    s3 = bench("vector rows (64d list)", infer, [("d", 64)], args.reps)

    worst = min(s1 + s2 + s3)
    print(f"\nworst-case native speedup: {worst:.2f}x "
          f"({'WIN' if worst > 1 else 'LOSS'})")


if __name__ == "__main__":
    main()
