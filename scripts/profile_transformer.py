"""Profile the transformer train step on the attached TPU and print the
top ops by self-time, grouped by fusion kind.

Same xplane pipeline as scripts/profile_resnet.py, pointed at the
flagship transformer config (dim 1024 / 8L / seq 2048, the sweep's
shape).  Defaults mirror the currently promoted bench_config.json
"transformer" section when one exists, so profiling the winner is just
``python scripts/profile_transformer.py --out TRANSFORMER_BREAKDOWN.md``.

Usage: python scripts/profile_transformer.py [--steps N] [--batch N]
    [--block-q N] [--block-kv N] [--remat {0,1,dots}]
    [--bwd {xla,pallas}] [--ce {dense,block}] [--out FILE.md]
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from profile_resnet import parse_xplane, summarize  # noqa: E402


def _promoted():
    import bench

    path = bench.bench_config_path()
    if os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f).get("transformer", {})
        except (OSError, ValueError):
            pass
    return {}


def main():
    promoted = _promoted()
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int,
                    default=int(promoted.get("batch", 32)))
    ap.add_argument("--block-q", type=int,
                    default=int(promoted.get("block_q", 512)))
    ap.add_argument("--block-kv", type=int,
                    default=int(promoted.get("block_kv", 512)))
    ap.add_argument("--remat", default=promoted.get("remat", True),
                    help="0/1 or the selective policy name dots")
    ap.add_argument("--bwd", choices=("xla", "pallas"),
                    default=promoted.get("bwd", "pallas"))
    ap.add_argument("--ce", choices=("dense", "block"),
                    default=promoted.get("ce", "dense"))
    ap.add_argument("--out", default=None,
                    help="also write the breakdown as markdown")
    args = ap.parse_args()
    remat = args.remat
    if remat in ("0", "False", False, 0):
        remat = False
    elif remat in ("1", "True", True, 1):
        remat = True
    elif remat != "dots":
        raise SystemExit(f"bad --remat {remat!r}")

    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from tensorflowonspark_tpu import ops
    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.utils import metrics as M

    tiny = os.environ.get("TFOS_PROFILE_TINY") == "1"  # off-chip smoke
    cfg = transformer.Config(
        vocab_size=512 if tiny else 16384,
        dim=128 if tiny else 1024,
        n_layers=2 if tiny else 8,
        n_heads=4 if tiny else 8,
        max_seq=128 if tiny else 2048,
        dtype="float32" if tiny else "bfloat16",
        attn_impl="flash",
    )
    if tiny:
        args.batch = 1
        args.block_q = args.block_kv = 128
    attn_fn = functools.partial(
        ops.flash_attention, causal=True, block_q=args.block_q,
        block_kv=args.block_kv, bwd_impl=args.bwd)
    ce_impl = "blockwise" if args.ce == "block" else "dense"

    print("init...", flush=True)
    opt = optax.adam(1e-3)

    @jax.jit
    def init_all(key):
        params = transformer.init(key, cfg)
        return params, opt.init(params)

    params, opt_state = init_all(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(
            0, cfg.vocab_size, (args.batch, cfg.max_seq)), jnp.int32)

    @jax.jit
    def run_steps(params, opt_state, tokens):
        def body(carry, _):
            p, o = carry
            loss, grads = jax.value_and_grad(transformer.loss_fn)(
                p, tokens, cfg, attn_fn=attn_fn, remat=remat,
                ce_impl=ce_impl, ce_block=min(2048, cfg.vocab_size))
            updates, o = opt.update(grads, o)
            return (optax.apply_updates(p, updates), o), loss
        (_, _), losses = lax.scan(body, (params, opt_state), None,
                                  length=args.steps)
        return losses[-1]

    print("compiling...", flush=True)
    float(run_steps(params, opt_state, tokens))
    t0 = time.perf_counter()
    float(run_steps(params, opt_state, tokens))
    dt = time.perf_counter() - t0
    ms_per_step = 1000 * dt / args.steps
    toks_per_sec = args.batch * cfg.max_seq / (dt / args.steps)
    peak = M.peak_flops() or 197e12
    mfu = toks_per_sec * M.transformer_flops_per_token(cfg) / peak
    print(f"step={ms_per_step:.1f}ms  tok/s={toks_per_sec:.0f}  "
          f"mfu={mfu:.4f}", flush=True)

    import shutil

    logdir = "/tmp/tfos_profile_transformer"
    shutil.rmtree(logdir, ignore_errors=True)
    jax.profiler.start_trace(logdir)
    float(run_steps(params, opt_state, tokens))
    jax.profiler.stop_trace()

    xspace = parse_xplane(logdir)
    from collections import defaultdict

    report = ["# Transformer step-time breakdown",
              "",
              f"dim={cfg.dim} layers={cfg.n_layers} seq={cfg.max_seq} "
              f"batch={args.batch} blocks=({args.block_q},{args.block_kv}) "
              f"remat={remat} bwd={args.bwd} ce={args.ce} "
              f"steps={args.steps}; measured {ms_per_step:.1f} ms/step "
              f"({toks_per_sec:.0f} tok/s, mfu {mfu:.4f}).",
              ""]
    for plane_name, totals, counts in summarize(xspace):
        total = sum(totals.values())
        print(f"\n== {plane_name}  total {total:.1f}ms over "
              f"{args.steps} steps ==")
        report += [f"## {plane_name} — {total:.1f} ms device time over "
                   f"{args.steps} steps", ""]
        groups = defaultdict(float)
        for name, ms in totals.items():
            key = name.split(".")[0].split("_")[0]
            groups[key] += ms
        report += ["| op group | ms | % |", "|---|---|---|"]
        for k, v in sorted(groups.items(), key=lambda kv: -kv[1])[:15]:
            print(f"  [group] {k:30s} {v:8.2f}ms {100 * v / total:5.1f}%")
            report.append(f"| {k} | {v:.2f} | {100 * v / total:.1f} |")
        print()
        report += ["", "| top op | ms | n | % |", "|---|---|---|---|"]
        for name, ms in sorted(totals.items(), key=lambda kv: -kv[1])[:40]:
            print(f"  {ms:8.2f}ms x{counts[name]:<4d} "
                  f"{100 * ms / total:5.1f}%  {name[:110]}")
            report.append(f"| `{name[:90]}` | {ms:.2f} | {counts[name]} "
                          f"| {100 * ms / total:.1f} |")
        report.append("")
    if args.out:
        with open(args.out, "w") as f:
            f.write("\n".join(report) + "\n")
        print(f"\nwrote {args.out}")


if __name__ == "__main__":
    main()
