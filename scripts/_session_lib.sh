# Shared helpers for the on-chip perf session scripts (sourced by
# tpu_perf_session.sh and tpu_round4_followup.sh; not executable).
# Requires: $log set by the caller; set -uo pipefail recommended.

# session_run <timeout_s> cmd... — one chip step under the tunnel
# watchdog (scripts/with_tunnel_watchdog.sh): killed within ~1 min of
# the relay dying (rc 86 -> session aborts; a dead relay is terminal),
# bounded by <timeout_s> (rc 124 logs and continues: partial results
# beat none), aborts on 126/127 (broken checkout must not silently
# burn the chip window).  TFOS_SESSION_SMOKE=1 disables the watchdog's
# port check (CPU dry runs have no relay to watch).
session_run() {
  local tmo=$1; shift
  echo "-- $* (watchdog ${tmo}s) --" | tee -a "$log"
  TFOS_WATCHDOG_DISABLE="${TFOS_SESSION_SMOKE:-0}" \
    bash scripts/with_tunnel_watchdog.sh "$tmo" "$@" 2>&1 | tee -a "$log"
  local rc=${PIPESTATUS[0]}
  echo "-- rc=$rc --" | tee -a "$log"
  if [ "$rc" = "86" ]; then
    echo "ABORT: relay died mid-step; nothing in the VM can restart it" \
      | tee -a "$log"
    exit 86
  fi
  if [ "$rc" = "127" ] || [ "$rc" = "126" ]; then
    echo "ABORT: step harness missing/not executable (rc=$rc)" \
      | tee -a "$log"
    exit "$rc"
  fi
}

# host_run <timeout_s> cmd... — a step that claims no TPU (e.g.
# stress_fed): plain timeout, no tunnel watchdog, never aborts.
host_run() {
  local tmo=$1; shift
  echo "-- $* (host, timeout ${tmo}s) --" | tee -a "$log"
  timeout -k 10 "$tmo" "$@" 2>&1 | tee -a "$log"
  echo "-- rc=${PIPESTATUS[0]} --" | tee -a "$log"
}

# probe_gate — bounded liveness probe BEFORE any big compile; ABORTS
# the session when the tunnel/pool is sick (rc 4 = relay port closed,
# diagnosed pre-jax in ~2 s; 124 = probe hang, TERM honored;
# 137 = probe hang, TERM ignored and KILL escalated — the wedged
# `import jax` signature; 2 = cpu backend; 3 = wrong result).
probe_gate() {
  echo "-- tpu_probe --" | tee -a "$log"
  # -k: a probe wedged in `import jax` against a dying relay ignores
  # TERM (observed r4) - escalate to KILL so no orphan holds the claim
  timeout -k 10 "${TFOS_SESSION_PROBE_TIMEOUT:-300}" python scripts/tpu_probe.py 2>&1 | tee -a "$log"
  local probe_rc=${PIPESTATUS[0]}
  echo "-- rc=$probe_rc --" | tee -a "$log"
  if [ "$probe_rc" != "0" ]; then
    echo "ABORT: TPU probe failed (rc=$probe_rc; 4=relay dead, \
124=timeout/hang, 137=hang+TERM-ignored(KILLed), 2=cpu backend, \
3=wrong result) - tunnel/pool is sick, not claiming further" | tee -a "$log"
    exit "$probe_rc"
  fi
}
