"""Merge per-node telemetry JSONL into one Chrome trace + summary.

Input: a directory of ``utils/telemetry.py`` JSONL files — either a
drained run directory (``$TFOS_TELEMETRY_DIR/run-<id>/``, written by
cluster shutdown) or ``$TFOS_TELEMETRY_DIR`` itself (driver files +
run dirs; scanned recursively).  Output:

  (a) a Chrome ``trace_event`` JSON (``--out``, default
      ``<dir>/trace.json``) loadable at https://ui.perfetto.dev — one
      process row per node_id, one thread row per source process;
  (b) a text summary on stdout: per-phase wall time, per-node step-time
      percentiles, infeed-stall fraction, and MFU when the ``train/step``
      spans carry ``flops_per_item``/``peak_flops`` attrs (the counting
      convention is utils/flops.py's: 2 FLOPs/MAC — TrainMetrics attaches
      both when constructed with a flops_per_item denominator);
  (c) with ``--trace <id>`` (any unique prefix of a trace_id): a
      single-request causal waterfall — every span/event carrying that
      trace_id across every node, indented by parent link — plus a
      critical-path summary decomposing the request into queue /
      prefill / decode / other milliseconds (the span tree is minted by
      ``utils/telemetry.py`` "Causal tracing").

Parity: the reference has no timeline tooling at all — its observability
is log lines (reference ``__init__.py:1-5``, SURVEY.md §5); this is the
aggregation half the telemetry tentpole adds on top.

Usage: python scripts/trace_merge.py DIR [--out trace.json]
           [--summary-out summary.txt]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

SCHEMA_KEYS = ("ts", "node_id", "role", "kind", "name", "dur_ms", "attrs")

_PID_RE = re.compile(r"-(\d+)\.jsonl$")


def load_records(run_dir):
    """((record, source_basename) list sorted by ts, skipped-line count).

    Scans ``run_dir`` recursively for ``*.jsonl`` so both a drained
    ``run-<id>/`` dir and a whole ``TFOS_TELEMETRY_DIR`` (driver files +
    run dirs) merge onto one timeline.  Malformed lines are counted, not
    fatal — a crashed writer's torn tail must not sink the merge.
    """
    out = []
    skipped = 0
    for root, _dirs, files in os.walk(run_dir):
        for name in sorted(files):
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(root, name)
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                skipped += 1
                continue
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    if not all(k in rec for k in SCHEMA_KEYS):
                        raise ValueError("missing schema keys")
                except (ValueError, TypeError):
                    skipped += 1
                    continue
                out.append((rec, name))
    out.sort(key=lambda p: p[0]["ts"])
    return out, skipped


def _source_pid(src):
    m = _PID_RE.search(src)
    return int(m.group(1)) if m else abs(hash(src)) % 100000


def to_chrome_trace(pairs):
    """Chrome ``trace_event`` dict from (record, source) pairs.

    Mapping: node_id -> trace pid (one process row per node), source
    file's OS pid -> trace tid (the executor and its forked trainer
    share a node row but get separate thread lanes, so overlapping spans
    never fake a nesting).  Spans are ``ph:"X"`` complete events, events
    are ``ph:"i"`` instants; timestamps are rebased to the earliest
    record (Perfetto handles epoch offsets, humans don't).
    """
    nodes = sorted({rec["node_id"] for rec, _ in pairs})
    pid_of = {n: i + 1 for i, n in enumerate(nodes)}
    t0 = min((rec["ts"] for rec, _ in pairs), default=0.0)
    events = []
    named_threads = set()
    for node in nodes:
        role = next(rec["role"] for rec, _ in pairs if rec["node_id"] == node)
        events.append({
            "ph": "M", "name": "process_name", "pid": pid_of[node],
            "tid": 0, "args": {"name": f"{node} ({role})"},
        })
    for rec, src in pairs:
        pid = pid_of[rec["node_id"]]
        tid = _source_pid(src)
        if (pid, tid) not in named_threads:
            named_threads.add((pid, tid))
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": src[:-len(".jsonl")]},
            })
        dur_ms = rec["dur_ms"]
        base = {
            "name": rec["name"],
            "cat": rec["role"],
            "pid": pid,
            "tid": tid,
            "args": rec["attrs"] or {},
        }
        if rec["kind"] == "span" and dur_ms is not None:
            base.update(
                ph="X",
                ts=(rec["ts"] - t0) * 1e6,
                dur=float(dur_ms) * 1e3,
            )
        else:
            base.update(ph="i", ts=(rec["ts"] - t0) * 1e6, s="t")
        events.append(base)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _pct(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize(pairs, skipped=0):
    """(text, stats) summary: per-phase wall, per-node step percentiles,
    infeed-stall fraction, MFU (when step spans carry the denominators).
    """
    recs = [rec for rec, _ in pairs]
    phases = {}
    per_node = {}
    serve = {"totals_ms": [], "queue_ms": [], "device_ms": [],
             "batches": [], "shed": 0}
    data_stages = {}
    actors = {"msgs": {}, "respawns": 0, "lost": 0, "redispatched": 0}
    for rec in recs:
        node = per_node.setdefault(
            rec["node_id"],
            {"role": rec["role"], "steps_ms": [], "items": 0,
             "model_flops": 0.0, "peak_flops": None, "infeed_s": 0.0},
        )
        if rec["name"] == "serve/shed":
            serve["shed"] += 1
        elif rec["name"] == "actor/respawn":
            actors["respawns"] += 1
        elif rec["name"] == "actor/lost":
            actors["lost"] += 1
        elif rec["name"] == "actor/redispatch":
            actors["redispatched"] += int(
                (rec["attrs"] or {}).get("asks") or 0)
        if rec["kind"] != "span" or rec["dur_ms"] is None:
            continue
        ph = phases.setdefault(rec["name"], {"count": 0, "total_ms": 0.0,
                                             "max_ms": 0.0})
        ph["count"] += 1
        ph["total_ms"] += rec["dur_ms"]
        ph["max_ms"] = max(ph["max_ms"], rec["dur_ms"])
        attrs = rec["attrs"] or {}
        if rec["name"] == "train/step":
            node["steps_ms"].append(float(rec["dur_ms"]))
            items = attrs.get("items") or 0
            node["items"] += items
            if attrs.get("flops_per_item"):
                node["model_flops"] += items * float(attrs["flops_per_item"])
            if attrs.get("peak_flops"):
                node["peak_flops"] = float(attrs["peak_flops"])
        elif rec["name"] == "feed/wait":
            node["infeed_s"] += float(rec["dur_ms"]) / 1e3
        elif rec["name"] == "data/stage":
            st = data_stages.setdefault(
                str(attrs.get("stage") or "?"),
                {"self_ms": [], "wait_ms": [], "records": 0})
            st["self_ms"].append(float(rec["dur_ms"]))
            st["wait_ms"].append(float(attrs.get("wait_ms") or 0.0))
            st["records"] += int(attrs.get("records") or 0)
        elif rec["name"] == "actor/message":
            key = (str(attrs.get("group") or "?"),
                   str(attrs.get("kind") or "?"))
            actors["msgs"].setdefault(key, []).append(float(rec["dur_ms"]))
        elif rec["name"] == "serve/request":
            serve["totals_ms"].append(float(rec["dur_ms"]))
            if attrs.get("queue_ms") is not None:
                serve["queue_ms"].append(float(attrs["queue_ms"]))
            if attrs.get("device_ms") is not None:
                serve["device_ms"].append(float(attrs["device_ms"]))
            if attrs.get("batch"):
                serve["batches"].append(float(attrs["batch"]))

    stats = {"records": len(recs), "skipped": skipped, "nodes": {},
             "phases": phases}
    span = ((max(r["ts"] for r in recs) - min(r["ts"] for r in recs))
            if recs else 0.0)
    lines = [
        f"telemetry summary: {len(per_node)} nodes, {len(recs)} records, "
        f"{span:.2f}s wall span"
        + (f", {skipped} unparseable lines skipped" if skipped else "")
    ]

    lines.append("")
    lines.append("-- phases (by total wall) --")
    lines.append(f"{'name':<32} {'count':>7} {'total_ms':>12} {'max_ms':>10}")
    for name, ph in sorted(phases.items(), key=lambda kv: -kv[1]["total_ms"]):
        lines.append(f"{name:<32} {ph['count']:>7} {ph['total_ms']:>12.1f} "
                     f"{ph['max_ms']:>10.1f}")

    if serve["totals_ms"] or serve["shed"]:
        # online-serving SLOs (docs/serving.md): per-request spans carry
        # queue/device decomposition; sheds are instant events
        totals = sorted(serve["totals_ms"])
        n_req = len(totals)
        shed = serve["shed"]
        stats["serving"] = {
            "requests": n_req,
            "shed": shed,
            "shed_rate": shed / (n_req + shed) if (n_req + shed) else 0.0,
            "p50_ms": _pct(totals, 0.50),
            "p95_ms": _pct(totals, 0.95),
            "p99_ms": _pct(totals, 0.99),
            "mean_queue_ms": (sum(serve["queue_ms"]) / len(serve["queue_ms"])
                              if serve["queue_ms"] else 0.0),
            "mean_device_ms": (sum(serve["device_ms"])
                               / len(serve["device_ms"])
                               if serve["device_ms"] else 0.0),
            "mean_device_batch": (sum(serve["batches"])
                                  / len(serve["batches"])
                                  if serve["batches"] else 0.0),
        }
        s = stats["serving"]
        lines.append("")
        lines.append("-- serving (serve/request spans) --")
        lines.append(
            f"requests={n_req} shed={shed} shed_rate={s['shed_rate']:.3f} "
            f"p50={s['p50_ms']:.1f}ms p95={s['p95_ms']:.1f}ms "
            f"p99={s['p99_ms']:.1f}ms")
        lines.append(
            f"mean queue={s['mean_queue_ms']:.1f}ms "
            f"device={s['mean_device_ms']:.1f}ms "
            f"device batch={s['mean_device_batch']:.1f}")

    if actors["msgs"] or actors["respawns"] or actors["lost"]:
        # supervised-actor health (docs/actors.md): per-message handler
        # latency by (group, kind); lost/respawn/redispatch counts are
        # the failover story of the run
        stats["actors"] = {
            "respawns": actors["respawns"],
            "lost": actors["lost"],
            "redispatched_asks": actors["redispatched"],
            "messages": {},
        }
        lines.append("")
        lines.append("-- actors (actor/message spans) --")
        lines.append(
            f"lost={actors['lost']} respawns={actors['respawns']} "
            f"redispatched_asks={actors['redispatched']}")
        if actors["msgs"]:
            lines.append(f"{'group':<16} {'kind':<16} {'count':>7} "
                         f"{'p50_ms':>9} {'p95_ms':>9} {'max_ms':>9}")
        for (group, kind), durs in sorted(actors["msgs"].items()):
            durs = sorted(durs)
            row = {"count": len(durs), "p50_ms": _pct(durs, 0.50),
                   "p95_ms": _pct(durs, 0.95), "max_ms": durs[-1]}
            stats["actors"]["messages"][f"{group}:{kind}"] = row
            lines.append(
                f"{group:<16} {kind:<16} {row['count']:>7} "
                f"{row['p50_ms']:>9.2f} {row['p95_ms']:>9.2f} "
                f"{row['max_ms']:>9.2f}")

    if data_stages:
        # input-pipeline stall attribution (docs/data.md): each
        # data/stage span is one produced block — dur_ms is the stage's
        # own produce time, attrs.wait_ms the time it blocked on its
        # upstream.  stall = wait / (wait + self): ~1.0 means the stage
        # starves (upstream-bound), ~0.0 means it is the bottleneck.
        stats["data"] = {}
        lines.append("")
        lines.append("-- data (data/stage spans) --")
        lines.append(
            f"{'stage':<16} {'blocks':>7} {'records':>9} {'self_p50':>9} "
            f"{'self_p95':>9} {'wait_p50':>9} {'wait_p95':>9} {'stall':>6}")
        for name in sorted(data_stages):
            st = data_stages[name]
            selfs = sorted(st["self_ms"])
            waits = sorted(st["wait_ms"])
            tot_self = sum(selfs)
            tot_wait = sum(waits)
            loop = tot_self + tot_wait
            stats["data"][name] = {
                "blocks": len(selfs), "records": st["records"],
                "self_p50_ms": _pct(selfs, 0.50),
                "self_p95_ms": _pct(selfs, 0.95),
                "wait_p50_ms": _pct(waits, 0.50),
                "wait_p95_ms": _pct(waits, 0.95),
                "stall_frac": tot_wait / loop if loop else 0.0,
            }
            d = stats["data"][name]
            lines.append(
                f"{name:<16} {d['blocks']:>7} {d['records']:>9} "
                f"{d['self_p50_ms']:>9.2f} {d['self_p95_ms']:>9.2f} "
                f"{d['wait_p50_ms']:>9.2f} {d['wait_p95_ms']:>9.2f} "
                f"{d['stall_frac']:>6.2f}")

    lines.append("")
    lines.append("-- per-node train steps --")
    lines.append(
        f"{'node':<16} {'role':<10} {'steps':>6} {'p50_ms':>8} {'p90_ms':>8} "
        f"{'p99_ms':>8} {'total_s':>8} {'infeed_s':>9} {'stall':>6} "
        f"{'mfu':>6}")
    for name in sorted(per_node):
        n = per_node[name]
        steps = sorted(n["steps_ms"])
        total_s = sum(steps) / 1e3
        # fraction of the train loop spent waiting on the feed: bounded
        # to [0, 1) even when waits dwarf compute (feeder-starved runs)
        loop_s = total_s + n["infeed_s"]
        stall = n["infeed_s"] / loop_s if loop_s else 0.0
        mfu = (n["model_flops"] / total_s / n["peak_flops"]
               if total_s and n["model_flops"] and n["peak_flops"] else None)
        stats["nodes"][name] = {
            "role": n["role"], "steps": len(steps),
            "p50_ms": _pct(steps, 0.50), "p90_ms": _pct(steps, 0.90),
            "p99_ms": _pct(steps, 0.99), "step_total_s": total_s,
            "infeed_wait_s": n["infeed_s"], "infeed_stall_frac": stall,
            "mfu": mfu, "items": n["items"],
        }
        s = stats["nodes"][name]
        lines.append(
            f"{name:<16} {n['role']:<10} {s['steps']:>6} {s['p50_ms']:>8.1f} "
            f"{s['p90_ms']:>8.1f} {s['p99_ms']:>8.1f} {total_s:>8.2f} "
            f"{n['infeed_s']:>9.3f} {stall:>6.2f} "
            f"{(f'{mfu:.3f}' if mfu is not None else '-'):>6}")
    return "\n".join(lines) + "\n", stats


# -- single-request causal view (--trace) ----------------------------------


def find_trace(pairs, needle):
    """Resolve ``needle`` (a full trace_id or any unique prefix) against
    every record's ``attrs.trace_id``.  Returns ``(full_id, records)``
    with the records ts-sorted; raises ``ValueError`` when nothing (or
    more than one trace) matches."""
    by_id = {}
    for rec, _src in pairs:
        tid = (rec.get("attrs") or {}).get("trace_id")
        if tid:
            by_id.setdefault(str(tid), []).append(rec)
    matches = sorted(t for t in by_id if t.startswith(str(needle)))
    if not matches:
        raise ValueError(
            f"no records carry trace_id {needle!r} "
            f"({len(by_id)} distinct traces in this directory)")
    if len(matches) > 1:
        heads = ", ".join(m[:16] for m in matches[:6])
        raise ValueError(
            f"trace prefix {needle!r} is ambiguous: {heads}"
            + ("…" if len(matches) > 6 else ""))
    tid = matches[0]
    return tid, sorted(by_id[tid], key=lambda r: r["ts"])


def _span_tree(recs):
    """(spans_by_id, children, roots, orphans) over one trace's records.

    Span ``ts`` is the START time (telemetry writes at exit with the
    entry timestamp), so tree + offsets need no reconstruction.  A span
    whose parent_id names a span that never reached any spool (e.g. its
    writer was SIGKILLed) is an *orphan* — reported, never dropped."""
    spans = {}
    for rec in recs:
        sid = (rec.get("attrs") or {}).get("span_id")
        if rec["kind"] == "span" and sid:
            spans[sid] = rec
    children = {}
    roots, orphans = [], []
    for sid, rec in spans.items():
        parent = (rec.get("attrs") or {}).get("parent_id")
        if parent and parent in spans:
            children.setdefault(parent, []).append(sid)
        elif parent:
            orphans.append(sid)
        else:
            roots.append(sid)
    def start(sid):
        return spans[sid]["ts"]

    for kids in children.values():
        kids.sort(key=start)
    roots.sort(key=start)
    orphans.sort(key=start)
    return spans, children, roots, orphans


def _bar(off_ms, dur_ms, wall_ms, width=30):
    if wall_ms <= 0:
        return ""
    lo = int(width * off_ms / wall_ms)
    hi = max(lo + 1, int(width * (off_ms + dur_ms) / wall_ms))
    return "[" + " " * lo + "#" * (hi - lo) + " " * (width - hi) + "]"


def render_waterfall(trace_id, recs):
    """One trace's records -> (waterfall + critical path text, stats)."""
    spans, children, roots, orphans = _span_tree(recs)
    events = [r for r in recs
              if r["kind"] != "span" or not (r.get("attrs") or {}).get(
                  "span_id")]
    t0 = min(r["ts"] for r in recs)
    t1 = max(r["ts"] + (r["dur_ms"] or 0.0) / 1e3 for r in recs)
    wall = (t1 - t0) * 1e3
    nodes = sorted({r["node_id"] for r in recs})
    lines = [f"trace {trace_id}: {len(spans)} spans, "
             f"{len(events)} events, {len(nodes)} nodes "
             f"({', '.join(nodes)}), {wall:.1f}ms wall"]
    lines.append("")
    lines.append(f"{'offset':>9} {'dur_ms':>9} {'node':<16} span")

    def emit(sid, depth):
        rec = spans[sid]
        off = (rec["ts"] - t0) * 1e3
        dur = float(rec["dur_ms"] or 0.0)
        name = ("  " * depth + ("└ " if depth else "") + rec["name"])
        lines.append(f"{off:>9.1f} {dur:>9.1f} {rec['node_id']:<16} "
                     f"{name:<34} {_bar(off, dur, wall)}")
        for kid in children.get(sid, ()):
            emit(kid, depth + 1)

    for root in roots:
        emit(root, 0)
    for sid in orphans:
        emit(sid, 0)
    if orphans:
        lines.append(f"  ({len(orphans)} span(s) whose parent never "
                     f"reached a spool — a writer died before flush?)")
    if events:
        lines.append("")
        lines.append("events:")
        for rec in events:
            off = (rec["ts"] - t0) * 1e3
            hints = {k: v for k, v in (rec.get("attrs") or {}).items()
                     if k in ("queue_ms", "sid", "slot", "reason", "depth")}
            hint = " ".join(f"{k}={v}" for k, v in sorted(hints.items()))
            lines.append(f"{off:>9.1f} {'·':>9} {rec['node_id']:<16} "
                         f"{rec['name']:<34} {hint}")

    # critical path: from the first root, always descend into the child
    # that finishes last — the chain that bounded the request's latency
    path = []
    if roots:
        sid = roots[0]
        while True:
            path.append(sid)
            kids = children.get(sid, ())
            if not kids:
                break
            sid = max(kids, key=lambda s: (spans[s]["ts"]
                                           + (spans[s]["dur_ms"] or 0) / 1e3))
    crit = decompose(recs, spans[roots[0]] if roots else None)
    lines.append("")
    lines.append(f"-- critical path ({len(path)} spans) --")
    if path:
        lines.append(" -> ".join(spans[s]["name"] for s in path))
    lines.append("-- request decomposition (ms) --")
    for k in ("queue", "prefill", "decode", "other", "total"):
        if crit.get(k) is not None:
            lines.append(f"{k:<8} {crit[k]:>9.1f}")
    stats = {"trace_id": trace_id, "spans": len(spans),
             "events": len(events), "nodes": nodes, "wall_ms": wall,
             "orphans": len(orphans),
             "critical_path": [spans[s]["name"] for s in path],
             "decomposition": crit}
    return "\n".join(lines) + "\n", stats


def decompose(recs, root):
    """Queue / prefill / decode / other milliseconds for one request.

    queue   = decode/admit's queue_ms (driver->replica admission wait);
    prefill = first-token latency minus the queue (ttft_ms rides
              decode/session and serve/generate result attrs);
    decode  = generation time (decode/retire's span duration) minus
              prefill; ``other`` is whatever of the root span the three
              phases don't explain: dispatch, transfer, uninstrumented.
    Every term is None when its source attr never appeared (a predict
    request has no decode phases)."""
    total = float(root["dur_ms"]) if root and root["dur_ms"] else None
    queue = ttft = gen = None
    for rec in recs:
        attrs = rec.get("attrs") or {}
        if attrs.get("queue_ms") is not None and queue is None:
            queue = float(attrs["queue_ms"])
        if attrs.get("ttft_ms") is not None and ttft is None:
            ttft = float(attrs["ttft_ms"])
        if rec["name"] == "decode/retire" and rec["dur_ms"] is not None:
            gen = float(rec["dur_ms"])
    out = {"total": total, "queue": queue, "prefill": None,
           "decode": None, "other": None}
    if ttft is not None:
        out["prefill"] = max(0.0, ttft - (queue or 0.0))
    if gen is not None:
        out["decode"] = max(0.0, gen - (out["prefill"] or 0.0))
    if total is not None:
        known = sum(v for v in (queue, out["prefill"], out["decode"])
                    if v is not None)
        out["other"] = max(0.0, total - known)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", help="telemetry dir (run-<id>/ or the root)")
    ap.add_argument("--out", default=None,
                    help="Chrome trace path (default <run_dir>/trace.json)")
    ap.add_argument("--summary-out", default=None,
                    help="also write the text summary to this path")
    ap.add_argument("--summary-json", default=None, metavar="OUT",
                    help="write the summary stats (the same numbers as "
                         "the text report) as JSON for CI / bench_check")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="render one request's causal waterfall + "
                         "critical path instead of the merged summary "
                         "(full trace_id or any unique prefix)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.run_dir):
        ap.error(f"not a directory: {args.run_dir}")
    pairs, skipped = load_records(args.run_dir)
    if not pairs:
        print(f"trace_merge: no telemetry records under {args.run_dir}",
              file=sys.stderr)
        return 1

    if args.trace:
        try:
            tid, recs = find_trace(pairs, args.trace)
        except ValueError as e:
            print(f"trace_merge: {e}", file=sys.stderr)
            return 1
        text, stats = render_waterfall(tid, recs)
        if args.summary_json:
            with open(args.summary_json, "w", encoding="utf-8") as f:
                json.dump(stats, f, indent=1, sort_keys=True, default=str)
                f.write("\n")
        sys.stdout.write(text)
        return 0

    out = args.out or os.path.join(args.run_dir, "trace.json")
    trace = to_chrome_trace(pairs)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    text, stats = summarize(pairs, skipped)
    if args.summary_out:
        with open(args.summary_out, "w", encoding="utf-8") as f:
            f.write(text)
    if args.summary_json:
        with open(args.summary_json, "w", encoding="utf-8") as f:
            json.dump(stats, f, indent=1, sort_keys=True, default=str)
            f.write("\n")
    sys.stdout.write(text)
    print(f"\nchrome trace: {out} ({len(trace['traceEvents'])} events) — "
          f"load at https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
