"""Measure this chip's achievable roofline: big-matmul TFLOP/s (MXU
ceiling) and big-elementwise GB/s (HBM ceiling).

Grounds MFU analysis in measured hardware numbers instead of datasheet
peaks: ResNet-50's step is HBM-bound (PERF.md round 4), so its MFU
ceiling is set by measured bandwidth, not the 197 TFLOP/s MXU figure.
Pairs with scripts/resnet_traffic.py (analytic model traffic floor).

Timing discipline (learned on-chip, r4): through the axon relay,
repeatedly dispatching the SAME jitted call with the SAME inputs and
waiting on ``block_until_ready`` measured 145 PFLOP/s on a 197 TFLOP/s
chip — dispatch (or a cached response), not compute.  Every probe here
therefore CHAINS: each call's output is the next call's input, so no
two requests are identical and the final 1-element value fetch cannot
resolve before every call has executed.

Usage: python scripts/roofline.py [--out ROOFLINE.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


# per-generation sanity ceilings, ~2x datasheet (HBM GB/s, bf16 TFLOP/s):
# a legitimate measurement can beat datasheet a little (clocks, cache
# effects), a dispatch artifact beats it by orders of magnitude.  The
# matched limits are stamped into the report so consumers
# (scripts/resnet_traffic.py) share them instead of duplicating.
_PHYSICS = [
    ("v5 lite", 1600, 400),   # v5e: 819 GB/s, 197 TFLOP/s
    ("v5p", 5500, 950),       # v5p: 2765 GB/s, 459 TFLOP/s
    ("v4", 2400, 550),        # v4: 1228 GB/s, 275 TFLOP/s
    ("v6", 3300, 1900),       # v6e: 1640 GB/s, 918 TFLOP/s
]
_DEFAULT_PHYSICS = (1600, 400)  # unknown TPU: assume v5e-class


def physics_limits(device_kind):
    kind = (device_kind or "").lower()
    for sub, gbs, tflops in _PHYSICS:
        if sub in kind:
            return gbs, tflops
    return _DEFAULT_PHYSICS


def _fetch(x):
    """True completion barrier: a 1-element read that depends on x."""
    import jax.numpy as jnp

    return float(np.asarray(jnp.ravel(x)[0]))


def _timed_chain(fn, x, *rest, iters=8):
    """Time ``iters`` chained calls x = fn(x, *rest); returns s/call."""
    x = fn(x, *rest)
    _fetch(x)  # compile + warmup + verified completion
    t0 = time.perf_counter()
    for _ in range(iters):
        x = fn(x, *rest)
    _fetch(x)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="also write JSON here")
    ap.add_argument("--iters", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]
    print(f"device: {dev} ({getattr(dev, 'device_kind', '?')})", flush=True)
    small = dev.platform == "cpu"
    max_gbs, max_tflops = physics_limits(getattr(dev, "device_kind", ""))
    report = {"device": str(dev), "platform": dev.platform,
              "sanity_max_gbs": max_gbs, "sanity_max_tflops": max_tflops}
    suspect = []

    # -- MXU ceiling: bf16 matmul chain, K large enough to amortize -----
    m = 2048 if small else 8192
    k = n = m
    steps = 4
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), jnp.bfloat16)
    b = jax.random.normal(key, (k, n), jnp.bfloat16)

    @jax.jit
    def mm(x, b):
        # 1/128 epilogue scale keeps the chained values bounded (fuses
        # into the matmul, no extra HBM traffic)
        def body(x, _):
            y = jnp.dot(x, b, preferred_element_type=jnp.bfloat16)
            return y * jnp.bfloat16(1.0 / 128.0), None

        y, _ = lax.scan(body, x, None, length=steps)
        return y

    dt = _timed_chain(mm, a, b, iters=args.iters)
    tflops = 2.0 * m * k * n * steps / dt / 1e12
    report["matmul_bf16_tflops"] = round(tflops, 1)
    print(f"bf16 matmul ({m}x{k}x{n} x{steps}): {tflops:.1f} TFLOP/s",
          flush=True)
    if not small and tflops > max_tflops:
        suspect.append("matmul_bf16_tflops")

    # -- HBM ceiling: elementwise scale-add (read + write) --------------
    nelem = (1 << 24) if small else (1 << 29)  # 1 GiB bf16 on TPU
    x = jax.random.normal(key, (nelem,), jnp.bfloat16)

    @jax.jit
    def ew(x):
        def body(y, _):
            return y * jnp.bfloat16(1.0001) + jnp.bfloat16(1e-6), None

        y, _ = lax.scan(body, x, None, length=steps)
        return y

    dt = _timed_chain(ew, x, iters=args.iters)
    gbs_ew = 2 * 2 * nelem * steps / dt / 1e9  # read + write, 2B/elem
    report["elementwise_gbs"] = round(gbs_ew, 1)
    print(f"elementwise r+w: {gbs_ew:.1f} GB/s", flush=True)
    if not small and gbs_ew > max_gbs:
        suspect.append("elementwise_gbs")

    # -- BN-shaped op: the ResNet hot pattern at its real shape ---------
    # (covers the reduction ceiling too: stats are a 2-sum reduce pass)
    bshape = (64, 56, 56, 256) if not small else (8, 16, 16, 32)
    xb = jax.random.normal(key, bshape, jnp.bfloat16)

    @jax.jit
    def bnlike(x):
        xf = x.astype(jnp.float32)
        ax = (0, 1, 2)
        nred = x.size // x.shape[-1]
        mean = jnp.sum(xf, axis=ax) / nred
        var = jnp.maximum(jnp.sum(xf * xf, axis=ax) / nred - mean * mean, 0)
        mul = lax.rsqrt(var + 1e-5).astype(x.dtype)
        add = (-mean * lax.rsqrt(var + 1e-5)).astype(x.dtype)
        return x * mul + add

    dt = _timed_chain(bnlike, xb, iters=args.iters)
    nb = int(np.prod(bshape))
    gbs_bn = 2 * (2 * nb + nb) / dt / 1e9  # stats read + norm read + write
    report["bn_fwd_gbs"] = round(gbs_bn, 1)
    print(f"bn-shaped fwd (stats+normalize, {bshape}): {gbs_bn:.1f} GB/s "
          f"effective", flush=True)
    if not small and gbs_bn > max_gbs:
        suspect.append("bn_fwd_gbs")

    if suspect:
        report["suspect"] = suspect
        print(f"WARNING: {suspect} exceed datasheet physics - timing "
              f"path compromised, numbers unusable", flush=True)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()
