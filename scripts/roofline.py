"""Measure this chip's achievable roofline: big-matmul TFLOP/s (MXU
ceiling) and big-elementwise + reduction GB/s (HBM ceiling).

Grounds MFU analysis in measured hardware numbers instead of datasheet
peaks: ResNet-50's step is HBM-bound (PERF.md round 4), so its MFU
ceiling is set by measured bandwidth, not the 197 TFLOP/s MXU figure.

Usage: python scripts/roofline.py [--out ROOFLINE.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _timed(fn, *args, iters=8):
    out = fn(*args)
    out.block_until_ready()  # compile + warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="also write JSON here")
    ap.add_argument("--iters", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]
    print(f"device: {dev} ({getattr(dev, 'device_kind', '?')})", flush=True)
    small = dev.platform == "cpu"
    report = {"device": str(dev), "platform": dev.platform}

    # -- MXU ceiling: bf16 matmul chain, K large enough to amortize -----
    m = 2048 if small else 8192
    k = n = m
    steps = 4
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), jnp.bfloat16)
    b = jax.random.normal(key, (k, n), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        # chain keeps the MXU busy across `steps` matmuls in ONE program
        def body(x, _):
            return jnp.dot(x, b, preferred_element_type=jnp.bfloat16), None
        y, _ = lax.scan(body, a, None, length=steps)
        return y

    dt = _timed(mm, a, b, iters=args.iters)
    tflops = 2.0 * m * k * n * steps / dt / 1e12
    report["matmul_bf16_tflops"] = round(tflops, 1)
    print(f"bf16 matmul ({m}x{k}x{n} x{steps}): {tflops:.1f} TFLOP/s",
          flush=True)

    # -- HBM ceiling 1: elementwise copy-scale (read + write) -----------
    nelem = (1 << 24) if small else (1 << 29)  # 1 GiB bf16 on TPU
    x = jax.random.normal(key, (nelem,), jnp.bfloat16)

    @jax.jit
    def ew(x):
        def body(y, _):
            return y * jnp.bfloat16(1.0001) + jnp.bfloat16(1e-6), None
        y, _ = lax.scan(body, x, None, length=steps)
        return y

    dt = _timed(ew, x, iters=args.iters)
    gbs_ew = 2 * 2 * nelem * steps / dt / 1e9  # read + write, 2B/elem
    report["elementwise_gbs"] = round(gbs_ew, 1)
    print(f"elementwise r+w: {gbs_ew:.1f} GB/s", flush=True)

    # -- HBM ceiling 2: reduction (read-only traffic) -------------------
    @jax.jit
    def red(x):
        xf = x.astype(jnp.float32)
        return jnp.sum(xf) + jnp.sum(xf * xf)

    dt = _timed(red, x, iters=args.iters)
    gbs_red = 2 * nelem / dt / 1e9
    report["reduce_gbs"] = round(gbs_red, 1)
    print(f"one-pass double reduce: {gbs_red:.1f} GB/s", flush=True)

    # -- BN-shaped op: the ResNet hot pattern at its real shape ---------
    bshape = (64, 56, 56, 256) if not small else (8, 16, 16, 32)
    xb = jax.random.normal(key, bshape, jnp.bfloat16)

    @jax.jit
    def bnlike(x):
        xf = x.astype(jnp.float32)
        ax = (0, 1, 2)
        nred = x.size // x.shape[-1]
        mean = jnp.sum(xf, axis=ax) / nred
        var = jnp.maximum(jnp.sum(xf * xf, axis=ax) / nred - mean * mean, 0)
        mul = lax.rsqrt(var + 1e-5).astype(x.dtype)
        add = (-mean * lax.rsqrt(var + 1e-5)).astype(x.dtype)
        return x * mul + add

    dt = _timed(bnlike, xb, iters=args.iters)
    nb = np.prod(bshape)
    gbs_bn = 2 * (2 * nb + nb) / dt / 1e9  # stats read + norm read + write
    report["bn_fwd_gbs"] = round(gbs_bn, 1)
    print(f"bn-shaped fwd (stats+normalize, {bshape}): {gbs_bn:.1f} GB/s "
          f"effective", flush=True)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()
